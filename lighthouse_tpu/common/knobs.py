"""Declarative registry of every ``LHTPU_*`` environment knob.

Before this module, ~30 raw ``os.environ`` reads were scattered across
the backend, the ops kernels, the resilience/health/pipeline commons and
the loadgen stack, each re-declaring its own default and parse rule —
and three shipped bug-fixes were instances of exactly that invariant
drift. Now every knob is declared ONCE here (name, kind, default, doc
line, consumer module) and read through :func:`knob`; the lint suite
(``tools/lint``, error family LH2xx) rejects any raw ``LHTPU_*`` read
outside this file, any default re-declared elsewhere, any unregistered
name passed to :func:`knob`, any registered knob with no consumer, and
a README knob table that drifts from :func:`knob_table_markdown`.

Parse rules (uniform across all knobs — previously each call site had
its own; ``bool`` knobs in particular were split between ``!= "0"`` and
``== "1"`` semantics):

* unset or empty string → the registered default;
* ``bool``   — ``0`` / ``false`` / ``no`` / ``off`` (case-insensitive)
  is False, anything else True;
* ``int`` / ``float`` — parsed; a malformed value falls back to the
  default instead of raising (a typo in an env var must not crash a
  serving process);
* ``optint`` — like ``int`` but the default is None ("auto"/"unset");
* ``str`` / ``optstr`` — the raw string; ``optstr`` defaults to None
  (tri-state knobs where unset means "decide from the backend").

Range clamps (e.g. "at least 2 sets per pipeline chunk") stay at the
consumer: they are consumer policy, not knob identity.

This module imports nothing from the rest of the package so every
layer — ops kernels, commons, loadgen, bench — can depend on it.
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from dataclasses import dataclass

__all__ = [
    "Knob", "REGISTRY", "knob", "maybe_int", "raw", "scoped_env",
    "knob_table_markdown",
]

_FALSE_WORDS = ("0", "false", "no", "off")


@dataclass(frozen=True)
class Knob:
    """One registered env knob. ``kind`` is the parse rule name,
    ``default`` the value :func:`knob` returns when unset/malformed,
    ``doc`` a one-line description (the README table row), ``consumer``
    the module that owns the policy built on it."""

    name: str
    kind: str       # bool | int | float | str | optint | optstr
    default: object
    doc: str
    consumer: str


# The single source of truth. Keep the table grouped by consumer; the
# README knob table is generated from it (tools/lint --knob-table) and
# lint LH203 fails when the checked-in copy drifts.
_ALL: tuple[Knob, ...] = (
    # ---------------------------------------------- jax_backend.py
    Knob("LHTPU_FUSED_VERIFY", "optstr", None,
         "Force fused Pallas verify (1) or classic XLA (0); unset = fused on TPU only",
         "lighthouse_tpu/jax_backend.py"),
    Knob("LHTPU_HOST_AGG", "optstr", None,
         "Force mixed-K host aggregation on (1) / off (0); unset = TPU heuristic S*K >= 2*keys",
         "lighthouse_tpu/jax_backend.py"),
    Knob("LHTPU_DEVICE_HTC", "optstr", None,
         "Force device hash-to-curve on (1) / off (0); unset = on when the backend is TPU",
         "lighthouse_tpu/jax_backend.py"),
    Knob("LHTPU_VERDICT_GROUPS", "int", 32,
         "Verdict groups per triage dispatch (rounded up to a power of two; 0 disables triage)",
         "lighthouse_tpu/jax_backend.py"),
    Knob("LHTPU_HOST_FALLBACK", "bool", True,
         "Serve tiny batches from the native CPU backend instead of paying the device tunnel",
         "lighthouse_tpu/jax_backend.py"),
    Knob("LHTPU_HOST_FALLBACK_MS", "float", 250.0,
         "Estimated-native-ms ceiling under which the host fallback takes the batch",
         "lighthouse_tpu/jax_backend.py"),
    Knob("LHTPU_MSM_VERIFY", "bool", True,
         "Use the MSM bucket schedule in the fused verify program (0 = per-lane scalar-mul scan)",
         "lighthouse_tpu/jax_backend.py"),
    # ------------------------------------------- common/resilience.py
    Knob("LHTPU_RESILIENCE", "bool", True,
         "0 disables retry + degradation ladder (raw raise-through)",
         "lighthouse_tpu/common/resilience.py"),
    Knob("LHTPU_RETRY_MAX", "int", 3,
         "Max transient retries per dispatch stage",
         "lighthouse_tpu/common/resilience.py"),
    Knob("LHTPU_RETRY_BASE_MS", "float", 50.0,
         "First retry backoff in ms (doubles per attempt)",
         "lighthouse_tpu/common/resilience.py"),
    Knob("LHTPU_RETRY_CAP_MS", "float", 2000.0,
         "Retry backoff ceiling in ms",
         "lighthouse_tpu/common/resilience.py"),
    Knob("LHTPU_RETRY_JITTER", "float", 0.25,
         "Jitter fraction added on top of each backoff",
         "lighthouse_tpu/common/resilience.py"),
    Knob("LHTPU_RETRY_SEED", "optstr", None,
         "Seed for the retry-jitter RNG (deterministic backoff schedules in tests/drills)",
         "lighthouse_tpu/common/resilience.py"),
    Knob("LHTPU_BREAKER_THRESHOLD", "int", 3,
         "Consecutive transient failures that open a dispatch-rung breaker",
         "lighthouse_tpu/common/resilience.py"),
    Knob("LHTPU_BREAKER_COOLDOWN_S", "float", 30.0,
         "Breaker open -> half-open probe delay in seconds",
         "lighthouse_tpu/common/resilience.py"),
    Knob("LHTPU_SYNC_DEADLINE_S", "float", 900.0,
         "device_sync force deadline in seconds (<= 0 runs inline, no deadline thread)",
         "lighthouse_tpu/common/resilience.py"),
    Knob("LHTPU_FAULT_INJECT", "str", "",
         "Deterministic fault injection spec: stage:kind:count[,...]",
         "lighthouse_tpu/common/resilience.py"),
    Knob("LHTPU_FAULT_HANG_S", "float", 3600.0,
         "Sleep length of the injected 'hang' fault kind in seconds",
         "lighthouse_tpu/common/resilience.py"),
    # --------------------------------------------- common/pipeline.py
    Knob("LHTPU_PIPELINE", "bool", True,
         "0 restores single-shot dispatch (no microbatch pipeline)",
         "lighthouse_tpu/common/pipeline.py"),
    Knob("LHTPU_PIPELINE_MIN_SETS", "int", 512,
         "Batches below this many sets stay single-shot",
         "lighthouse_tpu/common/pipeline.py"),
    Knob("LHTPU_PIPELINE_CHUNK", "optint", None,
         "Fixed power-of-two pipeline chunk size; unset = max(256, next_pow2(n)//4)",
         "lighthouse_tpu/common/pipeline.py"),
    # ---------------------------------------------- common/tracing.py
    Knob("LHTPU_TRACE", "bool", True,
         "0 disables span tracing (read once at import; flip at runtime via set_enabled)",
         "lighthouse_tpu/common/tracing.py"),
    # ----------------------------------------------- common/health.py
    Knob("LHTPU_RSS_WINDOW_S", "float", 60.0,
         "RSS-growth sentinel sliding window in seconds",
         "lighthouse_tpu/common/health.py"),
    Knob("LHTPU_RSS_GROWTH_MB", "float", 512.0,
         "RSS growth inside the window that reports degraded",
         "lighthouse_tpu/common/health.py"),
    Knob("LHTPU_RSS_CRITICAL_MB", "float", 16384.0,
         "Absolute RSS ceiling that reports critical",
         "lighthouse_tpu/common/health.py"),
    Knob("LHTPU_JIT_CACHE_MAX", "int", 512,
         "Jit-cache entry watermark; crossing fires one counted cache clear",
         "lighthouse_tpu/common/health.py"),
    Knob("LHTPU_CACHE_HIT_FLOOR", "float", 0.05,
         "Windowed input-cache hit rate below which the sentinel reports degraded",
         "lighthouse_tpu/common/health.py"),
    Knob("LHTPU_CACHE_MIN_SAMPLES", "int", 4096,
         "Input-cache lookups required in a window before the hit-rate floor applies",
         "lighthouse_tpu/common/health.py"),
    Knob("LHTPU_FLAP_WINDOW_S", "float", 60.0,
         "Breaker-flap sentinel sliding window in seconds",
         "lighthouse_tpu/common/health.py"),
    Knob("LHTPU_FLAP_MAX", "int", 6,
         "Breaker transitions inside the window that count as flapping",
         "lighthouse_tpu/common/health.py"),
    Knob("LHTPU_SLO_BREACH_STREAK", "int", 3,
         "Consecutive p99-over-budget reports that report degraded (2x = critical)",
         "lighthouse_tpu/common/health.py"),
    Knob("LHTPU_QUEUE_HIGH_FRAC", "float", 0.85,
         "Scheduler queue depth fraction of LHTPU_SCHED_QUEUE_CAP that counts as pressured",
         "lighthouse_tpu/common/health.py"),
    Knob("LHTPU_QUEUE_STREAK", "int", 3,
         "Consecutive pressured checks that report degraded (2x = critical)",
         "lighthouse_tpu/common/health.py"),
    # -------------------------------------------- parallel/engine.py
    Knob("LHTPU_DEVICES", "optint", None,
         "Cap on mesh device count; unset = every visible device (pow2-floored)",
         "lighthouse_tpu/parallel/engine.py"),
    Knob("LHTPU_SHARDED_VERIFY", "optstr", None,
         "Force sharded dispatch on (1) / off (0); unset = auto (TPU + enough sets per chip)",
         "lighthouse_tpu/parallel/engine.py"),
    Knob("LHTPU_SHARD_MIN_SETS", "int", 4,
         "Auto-sharding threshold: min real sets per chip before the mesh engages",
         "lighthouse_tpu/parallel/engine.py"),
    # ------------------------------------------------------ blsrt.py
    Knob("LHTPU_INPUT_CACHE", "bool", True,
         "0 disables the cross-call pubkey-row and hash-to-curve input caches",
         "lighthouse_tpu/blsrt.py"),
    Knob("LHTPU_PUBKEY_CACHE", "int", 65536,
         "Pubkey-row arena capacity (distinct pubkeys resident across calls)",
         "lighthouse_tpu/blsrt.py"),
    Knob("LHTPU_HTC_CACHE", "int", 4096,
         "Hash-to-curve output cache capacity (distinct messages)",
         "lighthouse_tpu/blsrt.py"),
    Knob("LHTPU_HTC_DEDUP", "bool", True,
         "0 disables protocol-aware message dedup before hash-to-curve (identity plan)",
         "lighthouse_tpu/blsrt.py"),
    Knob("LHTPU_HTC_BATCH_CACHE", "int", 8,
         "Device-resident distinct-message-batch output cache entries (0 disables)",
         "lighthouse_tpu/blsrt.py"),
    # -------------------------------------------------- ops kernels
    Knob("LHTPU_KS_CARRY", "bool", False,
         "Enable the Kogge-Stone carry-select normalization (TPU-lowering gated; see tkernel)",
         "lighthouse_tpu/ops/tkernel.py"),
    Knob("LHTPU_KS_CHECK", "bool", False,
         "Digit-range assertion inside carry normalization (debug; host-eval only)",
         "lighthouse_tpu/ops/tkernel.py"),
    Knob("LHTPU_MXU_FOLD", "optstr", None,
         "Force the MXU Montgomery fold on (1) / off (0); unset = on when the backend is TPU",
         "lighthouse_tpu/ops/tkernel.py"),
    Knob("LHTPU_LAZY_REDUCE", "bool", False,
         "Lazy-reduction tower arithmetic: normalize once per line function (hardware-gated; see tkernel)",
         "lighthouse_tpu/ops/tkernel.py"),
    Knob("LHTPU_MXU_CARRY", "bool", False,
         "Carry propagation as banded-Toeplitz MXU matmuls instead of serial chains (hardware-gated)",
         "lighthouse_tpu/ops/tkernel.py"),
    Knob("LHTPU_HTC_MXU_LADDER", "optstr", None,
         "Force Fp2 muln stacking in the ladder kernels on (1) / off (0); unset = follow the MXU fold",
         "lighthouse_tpu/ops/tkernel.py"),
    Knob("LHTPU_HTC_RESIDENT", "optstr", None,
         "Force the single resident hash-to-G2 map kernel on (1) / off (0); unset = on",
         "lighthouse_tpu/ops/tkernel_htc.py"),
    Knob("LHTPU_VMEM_LIMIT_MB", "int", 64,
         "Pallas compiler VMEM limit per kernel in MiB",
         "lighthouse_tpu/ops/tkernel.py"),
    Knob("LHTPU_PALLAS_MONT_MUL", "bool", False,
         "1 routes mont_mul through the Pallas kernel instead of the XLA path",
         "lighthouse_tpu/ops/limb.py"),
    # ------------------------------------------------ loadgen/serve.py
    Knob("LHTPU_BATCH_TARGET", "int", 256,
         "Full-batch dispatch size for the serving loop",
         "lighthouse_tpu/loadgen/serve.py"),
    Knob("LHTPU_BATCH_DEADLINE_MS", "float", 250.0,
         "Partial-batch latency budget: a held batch fires at this deadline",
         "lighthouse_tpu/loadgen/serve.py"),
    Knob("LHTPU_ADMIT_HIGH", "int", 8192,
         "Sheddable queue depth at which the admission gate closes",
         "lighthouse_tpu/loadgen/serve.py"),
    Knob("LHTPU_ADMIT_LOW", "optint", None,
         "Queue depth at which the gate reopens; unset = admit_high // 2",
         "lighthouse_tpu/loadgen/serve.py"),
    Knob("LHTPU_SLO_BUDGET_MS", "float", 4000.0,
         "p99 enqueue->verdict budget for the within_budget SLO verdict",
         "lighthouse_tpu/loadgen/serve.py"),
    # -------------------------------------------------- loadgen/slo.py
    Knob("LHTPU_SLO_SAMPLE_CAP", "int", 8192,
         "Per-work-type latency sample window (exact quantiles within it); totals stay exact",
         "lighthouse_tpu/loadgen/slo.py"),
    # -------------------------------------------- loadgen/scheduler.py
    Knob("LHTPU_SCHED_BLOCK_DEADLINE_MS", "float", 0.0,
         "Block-class coalescing deadline; 0 = dispatch immediately, preempting any window",
         "lighthouse_tpu/loadgen/scheduler.py"),
    Knob("LHTPU_SCHED_AGG_DEADLINE_MS", "float", 100.0,
         "Aggregate-class coalescing deadline before a partial batch fires",
         "lighthouse_tpu/loadgen/scheduler.py"),
    Knob("LHTPU_SCHED_ATT_DEADLINE_MS", "float", 250.0,
         "Attestation-class coalescing deadline before a partial batch fires",
         "lighthouse_tpu/loadgen/scheduler.py"),
    Knob("LHTPU_SCHED_SYNC_DEADLINE_MS", "float", 500.0,
         "Sync-class coalescing deadline before a partial batch fires",
         "lighthouse_tpu/loadgen/scheduler.py"),
    Knob("LHTPU_SCHED_QUEUE_CAP", "int", 16384,
         "Per-class queue capacity in the continuous scheduler (shed watermarks scale off it)",
         "lighthouse_tpu/loadgen/scheduler.py"),
    Knob("LHTPU_SCHED_TENANT_QUOTA", "float", 0.5,
         "Max fraction of a class's shed watermark one tenant may occupy before its offers shed",
         "lighthouse_tpu/loadgen/scheduler.py"),
    Knob("LHTPU_SCHED_DISPATCH_MS", "float", 0.0,
         "Modeled per-chunk device occupancy on the virtual clock (enables deterministic preemption windows)",
         "lighthouse_tpu/loadgen/scheduler.py"),
    Knob("LHTPU_SCHED_CACHE", "bool", True,
         "Cross-slot committee-composition pubkey cache on (1) / off (0)",
         "lighthouse_tpu/loadgen/scheduler.py"),
    Knob("LHTPU_SCHED_CACHE_CAP", "int", 4096,
         "Composition-cache entry capacity (LRU beyond it)",
         "lighthouse_tpu/loadgen/scheduler.py"),
    Knob("LHTPU_SCHED_SLASHING_DEADLINE_MS", "float", 50.0,
         "Slashing-class coalescing deadline before a partial batch fires",
         "lighthouse_tpu/loadgen/scheduler.py"),
    Knob("LHTPU_SCHED_STARVATION_MS", "float", 1000.0,
         "Oldest-event wait past which a non-block class outranks priority order (0 disables)",
         "lighthouse_tpu/loadgen/scheduler.py"),
    Knob("LHTPU_SCHED_SLASHER", "bool", True,
         "Feed slashing-event votes through the surround/double-vote slasher sink",
         "lighthouse_tpu/loadgen/scheduler.py"),
    # ---------------------------------------------------- slasher/arrays.py
    Knob("LHTPU_SLASHER_DEVICE", "optstr", None,
         "Force the device slasher planes on (1) / off (0); unset = on when jax imports",
         "lighthouse_tpu/slasher/arrays.py"),
    Knob("LHTPU_SLASHER_CHUNK", "int", 256,
         "Validators per device slasher plane chunk",
         "lighthouse_tpu/slasher/arrays.py"),
    Knob("LHTPU_SLASHER_HISTORY", "int", 4096,
         "Epoch ring length of the device slasher min/max-target planes",
         "lighthouse_tpu/slasher/arrays.py"),
    # ------------------------------------------------- loadgen/soak.py
    Knob("LHTPU_CHAOS_SCHEDULE", "str", "",
         "Soak chaos plan: epoch:stage:kind:count[;...] layered on the fault injector",
         "lighthouse_tpu/loadgen/soak.py"),
    Knob("LHTPU_SOAK_LEAK_MB", "float", 512.0,
         "RSS growth budget between the second and last soak epoch before the verdict fails",
         "lighthouse_tpu/loadgen/soak.py"),
    Knob("LHTPU_SOAK_WATCHDOG_K", "float", 20.0,
         "Epoch watchdog budget multiplier over the scaled epoch length",
         "lighthouse_tpu/loadgen/soak.py"),
    Knob("LHTPU_SOAK_WATCHDOG_MIN_S", "float", 300.0,
         "Epoch watchdog budget floor in seconds (must clear a cold XLA compile)",
         "lighthouse_tpu/loadgen/soak.py"),
    Knob("LHTPU_WEATHER_SCHEDULE", "str", "",
         "Chain-weather plan: epoch:axis:value[;...] over the traffic weather axes",
         "lighthouse_tpu/loadgen/soak.py"),
)

REGISTRY: dict[str, Knob] = {k.name: k for k in _ALL}
assert len(REGISTRY) == len(_ALL), "duplicate knob registration"


def knob(name: str):
    """The current typed value of a registered knob (env is re-read on
    every call — the PR 1 trace-time convention; knobs read once at
    import say so in their doc line). Unregistered names raise KeyError
    loudly: registering is the point."""
    k = REGISTRY[name]
    raw_v = os.environ.get(name)
    if raw_v is None or raw_v == "":
        return k.default
    if k.kind == "bool":
        return raw_v.strip().lower() not in _FALSE_WORDS
    if k.kind in ("int", "optint"):
        try:
            return int(raw_v)
        except ValueError:
            return k.default
    if k.kind == "float":
        try:
            return float(raw_v)
        except ValueError:
            return k.default
    return raw_v  # str / optstr


def maybe_int(name: str, default: int | None = None) -> int:
    """Integer env read for DYNAMIC names (e.g. a cache whose env var
    is injected by tests): registered names parse through :func:`knob`
    (their registry default wins; the caller's is ignored), unregistered
    ones parse raw with the caller's default."""
    if name in REGISTRY:
        return int(knob(name))
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        if default is None:
            raise KeyError(
                f"{name} is unregistered, unset, and has no caller default"
            ) from None
        return default


def raw(name: str) -> str | None:
    """The raw env string of a knob (None when unset) — for
    save/restore blocks and spec-change detection, where the unparsed
    identity matters, not the typed value."""
    return os.environ.get(name)


@contextmanager
def scoped_env(overrides: dict[str, str | None]):
    """Set (value) or unset (None) env knobs for a ``with`` block and
    restore the previous state on exit — the save/set/restore pattern
    bench sweeps and fault drills used to hand-roll."""
    saved = {k: os.environ.get(k) for k in overrides}
    try:
        for k, v in overrides.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = str(v)
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def knob_table_markdown() -> str:
    """The README knob table, generated from the registry. Checked in
    under the ``<!-- knob-table:begin -->`` markers; lint LH203 fails
    when the checked-in copy no longer matches this output."""
    rows = [
        "| Knob | Type | Default | Consumer | Description |",
        "|---|---|---|---|---|",
    ]
    for k in _ALL:
        default = "*(auto)*" if k.default is None else f"`{k.default}`"
        consumer = k.consumer.replace("lighthouse_tpu/", "")
        rows.append(
            f"| `{k.name}` | {k.kind} | {default} | `{consumer}` | {k.doc} |"
        )
    return "\n".join(rows)
