"""Embedded network configs (reference: common/eth2_network_config —
built-in config.yaml + boot nodes + genesis per network, baked in via
include_bytes and melted into ChainSpec).

Networks here carry the YAML-equivalent dicts inline (no genesis.ssz
blobs: interop/checkpoint genesis cover this framework's boot paths)
and apply themselves onto a ChainSpec.
"""

from __future__ import annotations

import dataclasses

from ..consensus.config import ChainSpec, mainnet_spec, minimal_spec

BUILT_IN: dict[str, dict] = {
    "mainnet": {
        "PRESET_BASE": "mainnet",
        "MIN_GENESIS_ACTIVE_VALIDATOR_COUNT": 16384,
        "MIN_GENESIS_TIME": 1606824000,
        "GENESIS_DELAY": 604800,
        "GENESIS_FORK_VERSION": "0x00000000",
        "ALTAIR_FORK_VERSION": "0x01000000",
        "ALTAIR_FORK_EPOCH": 74240,
        "BELLATRIX_FORK_VERSION": "0x02000000",
        "BELLATRIX_FORK_EPOCH": 144896,
        "SECONDS_PER_SLOT": 12,
        "ETH1_FOLLOW_DISTANCE": 2048,
        "DEPOSIT_CHAIN_ID": 1,
        "boot_enr": [],
    },
    "prater": {
        "PRESET_BASE": "mainnet",
        "MIN_GENESIS_ACTIVE_VALIDATOR_COUNT": 16384,
        "MIN_GENESIS_TIME": 1614588812,
        "GENESIS_FORK_VERSION": "0x00001020",
        "ALTAIR_FORK_VERSION": "0x01001020",
        "ALTAIR_FORK_EPOCH": 36660,
        "BELLATRIX_FORK_VERSION": "0x02001020",
        "BELLATRIX_FORK_EPOCH": 112260,
        "SECONDS_PER_SLOT": 12,
        "ETH1_FOLLOW_DISTANCE": 2048,
        "DEPOSIT_CHAIN_ID": 5,
        "boot_enr": [],
    },
    "minimal-interop": {
        "PRESET_BASE": "minimal",
        "MIN_GENESIS_ACTIVE_VALIDATOR_COUNT": 64,
        "GENESIS_FORK_VERSION": "0x00000001",
        "SECONDS_PER_SLOT": 6,
        "ETH1_FOLLOW_DISTANCE": 16,
        "boot_enr": [],
    },
}


def _ver(v: str) -> bytes:
    return bytes.fromhex(v.removeprefix("0x"))


def spec_for_network(name: str) -> ChainSpec:
    """Melt a built-in network config into a ChainSpec
    (eth2_network_config/src/lib.rs apply_to_chain_spec)."""
    cfg = BUILT_IN.get(name)
    if cfg is None:
        raise KeyError(f"unknown network {name!r}; have {sorted(BUILT_IN)}")
    base = minimal_spec() if cfg["PRESET_BASE"] == "minimal" else mainnet_spec()
    updates: dict = {"name": name}
    for key in (
        "MIN_GENESIS_ACTIVE_VALIDATOR_COUNT", "MIN_GENESIS_TIME",
        "GENESIS_DELAY", "SECONDS_PER_SLOT", "ETH1_FOLLOW_DISTANCE",
        "ALTAIR_FORK_EPOCH", "BELLATRIX_FORK_EPOCH",
    ):
        if key in cfg and hasattr(base, key):
            updates[key] = cfg[key]
    for key in (
        "GENESIS_FORK_VERSION", "ALTAIR_FORK_VERSION", "BELLATRIX_FORK_VERSION",
    ):
        if key in cfg and hasattr(base, key):
            updates[key] = _ver(cfg[key])
    return dataclasses.replace(base, **updates)


def boot_nodes(name: str) -> list[str]:
    return list(BUILT_IN.get(name, {}).get("boot_enr", []))
