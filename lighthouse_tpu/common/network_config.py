"""Embedded network configs (reference: common/eth2_network_config —
built-in config.yaml + boot nodes + genesis per network, baked in via
include_bytes and melted into ChainSpec).

Networks here carry the YAML-equivalent dicts inline (no genesis.ssz
blobs: interop/checkpoint genesis cover this framework's boot paths)
and apply themselves onto a ChainSpec.
"""

from __future__ import annotations

import dataclasses

from ..consensus.config import ChainSpec, mainnet_spec, minimal_spec

BUILT_IN: dict[str, dict] = {
    "mainnet": {
        "PRESET_BASE": "mainnet",
        "MIN_GENESIS_ACTIVE_VALIDATOR_COUNT": 16384,
        "MIN_GENESIS_TIME": 1606824000,
        "GENESIS_DELAY": 604800,
        "GENESIS_FORK_VERSION": "0x00000000",
        "ALTAIR_FORK_VERSION": "0x01000000",
        "ALTAIR_FORK_EPOCH": 74240,
        "BELLATRIX_FORK_VERSION": "0x02000000",
        "BELLATRIX_FORK_EPOCH": 144896,
        "SECONDS_PER_SLOT": 12,
        "ETH1_FOLLOW_DISTANCE": 2048,
        "DEPOSIT_CHAIN_ID": 1,
        "boot_enr": [],
    },
    "prater": {
        "PRESET_BASE": "mainnet",
        "MIN_GENESIS_ACTIVE_VALIDATOR_COUNT": 16384,
        "MIN_GENESIS_TIME": 1614588812,
        "GENESIS_FORK_VERSION": "0x00001020",
        "ALTAIR_FORK_VERSION": "0x01001020",
        "ALTAIR_FORK_EPOCH": 36660,
        "BELLATRIX_FORK_VERSION": "0x02001020",
        "BELLATRIX_FORK_EPOCH": 112260,
        "SECONDS_PER_SLOT": 12,
        "ETH1_FOLLOW_DISTANCE": 2048,
        "DEPOSIT_CHAIN_ID": 5,
        "boot_enr": [],
    },
    "gnosis": {
        "PRESET_BASE": "gnosis",
        "MIN_GENESIS_ACTIVE_VALIDATOR_COUNT": 4096,
        "MIN_GENESIS_TIME": 1638968400,
        "GENESIS_DELAY": 6000,
        "GENESIS_FORK_VERSION": "0x00000064",
        "ALTAIR_FORK_VERSION": "0x01000064",
        "ALTAIR_FORK_EPOCH": 512,
        "BELLATRIX_FORK_VERSION": "0x02000064",
        "BELLATRIX_FORK_EPOCH": 385536,
        "SECONDS_PER_SLOT": 5,
        "ETH1_FOLLOW_DISTANCE": 1024,
        "DEPOSIT_CHAIN_ID": 100,
        "boot_enr": [],
    },
    "sepolia": {
        "PRESET_BASE": "mainnet",
        "MIN_GENESIS_ACTIVE_VALIDATOR_COUNT": 1300,
        "MIN_GENESIS_TIME": 1655647200,
        "GENESIS_DELAY": 86400,
        "GENESIS_FORK_VERSION": "0x90000069",
        "ALTAIR_FORK_VERSION": "0x90000070",
        "ALTAIR_FORK_EPOCH": 50,
        "BELLATRIX_FORK_VERSION": "0x90000071",
        "BELLATRIX_FORK_EPOCH": 100,
        "SECONDS_PER_SLOT": 12,
        "ETH1_FOLLOW_DISTANCE": 2048,
        "DEPOSIT_CHAIN_ID": 11155111,
        "boot_enr": [],
    },
    "ropsten": {
        "PRESET_BASE": "mainnet",
        "MIN_GENESIS_ACTIVE_VALIDATOR_COUNT": 100000,
        "MIN_GENESIS_TIME": 1653318000,
        "GENESIS_DELAY": 604800,
        "GENESIS_FORK_VERSION": "0x80000069",
        "ALTAIR_FORK_VERSION": "0x80000070",
        "ALTAIR_FORK_EPOCH": 500,
        "BELLATRIX_FORK_VERSION": "0x80000071",
        "BELLATRIX_FORK_EPOCH": 750,
        "SECONDS_PER_SLOT": 12,
        "ETH1_FOLLOW_DISTANCE": 2048,
        "DEPOSIT_CHAIN_ID": 3,
        "boot_enr": [],
    },
    "kiln": {
        "PRESET_BASE": "mainnet",
        "MIN_GENESIS_ACTIVE_VALIDATOR_COUNT": 95000,
        "MIN_GENESIS_TIME": 1647007200,
        "GENESIS_DELAY": 300,
        "GENESIS_FORK_VERSION": "0x70000069",
        "ALTAIR_FORK_VERSION": "0x70000070",
        "ALTAIR_FORK_EPOCH": 50,
        "BELLATRIX_FORK_VERSION": "0x70000071",
        "BELLATRIX_FORK_EPOCH": 150,
        "SECONDS_PER_SLOT": 12,
        "ETH1_FOLLOW_DISTANCE": 2048,
        "DEPOSIT_CHAIN_ID": 1337802,
        "boot_enr": [],
    },
    "minimal-interop": {
        "PRESET_BASE": "minimal",
        "MIN_GENESIS_ACTIVE_VALIDATOR_COUNT": 64,
        "GENESIS_FORK_VERSION": "0x00000001",
        "SECONDS_PER_SLOT": 6,
        "ETH1_FOLLOW_DISTANCE": 16,
        "boot_enr": [],
    },
}


def _ver(v: str) -> bytes:
    return bytes.fromhex(v.removeprefix("0x"))


def spec_for_network(name: str) -> ChainSpec:
    """Melt a built-in network config into a ChainSpec
    (eth2_network_config/src/lib.rs apply_to_chain_spec)."""
    cfg = BUILT_IN.get(name)
    if cfg is None:
        raise KeyError(f"unknown network {name!r}; have {sorted(BUILT_IN)}")
    base = minimal_spec() if cfg["PRESET_BASE"] == "minimal" else mainnet_spec()
    if cfg["PRESET_BASE"] == "gnosis":
        # Gnosis runs its own preset (eth_spec.rs gnosis feature):
        # 16-slot epochs and a 512-epoch sync-committee period on
        # otherwise-mainnet sizes.
        base = dataclasses.replace(
            base,
            preset=dataclasses.replace(
                base.preset,
                SLOTS_PER_EPOCH=16,
                EPOCHS_PER_SYNC_COMMITTEE_PERIOD=512,
            ),
        )
    updates: dict = {"name": name}
    for key in (
        "MIN_GENESIS_ACTIVE_VALIDATOR_COUNT", "MIN_GENESIS_TIME",
        "GENESIS_DELAY", "SECONDS_PER_SLOT", "ETH1_FOLLOW_DISTANCE",
        "ALTAIR_FORK_EPOCH", "BELLATRIX_FORK_EPOCH", "DEPOSIT_CHAIN_ID",
    ):
        if key in cfg and hasattr(base, key):
            updates[key] = cfg[key]
    for key in (
        "GENESIS_FORK_VERSION", "ALTAIR_FORK_VERSION", "BELLATRIX_FORK_VERSION",
    ):
        if key in cfg and hasattr(base, key):
            updates[key] = _ver(cfg[key])
    return dataclasses.replace(base, **updates)


def boot_nodes(name: str) -> list[str]:
    return list(BUILT_IN.get(name, {}).get("boot_enr", []))


def load_testnet_dir(path: str):
    """Boot from an `lcli new-testnet` bundle (or any directory in the
    eth2_network_config layout): config.yaml + genesis.ssz [+
    boot_enr.yaml]. Returns (ChainSpec, genesis_state_bytes, boot_enrs)
    — the testnet-dir twin of the reference's Eth2NetworkConfig::load
    (eth2_network_config/src/lib.rs)."""
    import os

    import yaml as _yaml

    with open(os.path.join(path, "config.yaml")) as f:
        cfg = _yaml.safe_load(f) or {}

    base = minimal_spec() if cfg.get("PRESET_BASE") == "minimal" else mainnet_spec()
    updates: dict = {"name": cfg.get("CONFIG_NAME", os.path.basename(path))}
    for key in (
        "MIN_GENESIS_ACTIVE_VALIDATOR_COUNT", "MIN_GENESIS_TIME",
        "GENESIS_DELAY", "SECONDS_PER_SLOT", "ETH1_FOLLOW_DISTANCE",
        "ALTAIR_FORK_EPOCH", "BELLATRIX_FORK_EPOCH", "DEPOSIT_CHAIN_ID",
    ):
        if key in cfg and hasattr(base, key):
            updates[key] = int(cfg[key])
    for key in (
        "GENESIS_FORK_VERSION", "ALTAIR_FORK_VERSION", "BELLATRIX_FORK_VERSION",
    ):
        if key in cfg and hasattr(base, key):
            v = cfg[key]
            # YAML 1.1 reads 0x-literals as ints; quoted values stay str.
            updates[key] = (
                v.to_bytes(4, "big") if isinstance(v, int) else _ver(v)
            )
    spec = dataclasses.replace(base, **updates)

    with open(os.path.join(path, "genesis.ssz"), "rb") as f:
        genesis = f.read()
    enrs: list[str] = []
    enr_path = os.path.join(path, "boot_enr.yaml")
    if os.path.exists(enr_path):
        with open(enr_path) as f:
            enrs = _yaml.safe_load(f) or []
    return spec, genesis, enrs
