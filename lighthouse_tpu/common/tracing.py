"""Hot-path span tracing — the structural half of the metrics layer.

Reference Lighthouse instruments every crate with a ``metrics.rs``
against the global ``lighthouse_metrics`` registry scraped by
``http_metrics``; histograms alone, though, cannot say *where inside*
``JaxBlsBackend._dispatch`` a batch spent its time or died. A ``Span``
is a timed context manager: spans nest through a thread-local stack,
finished roots land in a bounded ring buffer, and every span's duration
is mirrored into registry histograms — the shared ``lhtpu_span_seconds``
family labelled by span name, plus an optional caller-supplied histogram
with its own labels — so ONE instrumentation point feeds the Prometheus
scrape (``/metrics``), the Chrome-trace export (``/trace`` or
``chrome_trace()``), and the bench's per-stage breakdown.

Overhead discipline: with ``LHTPU_TRACE=0`` every ``span()`` call
returns the shared no-op span — no clock read, no allocation, nothing
on the measured path. Enabled is the default: one span costs ~1 µs
against millisecond-scale dispatch stages.

Usage::

    from lighthouse_tpu.common import tracing

    with tracing.span("bls_dispatch/pack", sets=n) as sp:
        ...
        sp.set(padded=S)

    tracing.chrome_trace()   # -> chrome://tracing / Perfetto events
    tracing.to_dicts()       # -> JSON-able nested span tree
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from . import knobs
from .metrics import REGISTRY, Histogram

#: finished ROOT spans kept for export (children ride their root)
MAX_ROOT_SPANS = 256

_enabled = bool(knobs.knob("LHTPU_TRACE"))


def enabled() -> bool:
    """Is tracing on? (LHTPU_TRACE=0 disables; read once at import,
    flip at runtime with :func:`set_enabled`)."""
    return _enabled


def set_enabled(on: bool) -> bool:
    """Enable/disable tracing at runtime; returns the previous state."""
    global _enabled
    prev = _enabled
    _enabled = bool(on)
    return prev


#: every finished span mirrors its duration here, labelled by span name
SPAN_SECONDS = REGISTRY.histogram(
    "lhtpu_span_seconds",
    "Duration of tracing spans, labelled by span name",
    ("span",),
)


class _NullSpan:
    """The shared do-nothing span handed out when tracing is off."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs) -> None:
        pass


NULL_SPAN = _NullSpan()


class Span:
    """One timed region. Context manager; nests via the owning tracer's
    thread-local stack. ``metric``/``labels``: an extra Histogram to
    mirror the duration into (on top of ``lhtpu_span_seconds``)."""

    __slots__ = (
        "name", "attrs", "start", "end", "children", "tid",
        "_tracer", "_metric", "_labels",
    )

    def __init__(self, tracer: "Tracer", name: str,
                 metric: Histogram | None, labels: dict | None, attrs: dict):
        self.name = name
        self.attrs = attrs
        self.start = 0.0
        self.end: float | None = None
        self.children: list[Span] = []
        self.tid = threading.get_ident()
        self._tracer = tracer
        self._metric = metric
        self._labels = labels or {}

    @property
    def duration(self) -> float | None:
        return None if self.end is None else self.end - self.start

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def __enter__(self) -> "Span":
        self._tracer._stack().append(self)
        self.start = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.end = time.perf_counter()
        if exc_type is not None:
            # failures stay attributed even when the caller re-raises
            self.attrs.setdefault("error", exc_type.__name__)
        stack = self._tracer._stack()
        if self in stack:  # tolerate interleaved exits
            while stack and stack[-1] is not self:
                stack.pop()
            if stack:
                stack.pop()
        dur = self.end - self.start
        SPAN_SECONDS.observe(dur, span=self.name)
        if self._metric is not None:
            self._metric.observe(dur, **self._labels)
        if stack:
            stack[-1].children.append(self)
        else:
            self._tracer._add_root(self)
        return False

    # ------------------------------------------------------------- export
    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "start": self.start,
            "duration": self.duration,
            "tid": self.tid,
        }
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d


class Tracer:
    """Thread-local span stacks + a bounded ring of finished roots."""

    def __init__(self, max_roots: int = MAX_ROOT_SPANS):
        self._local = threading.local()
        self._lock = threading.Lock()
        self._roots: deque[Span] = deque(maxlen=max_roots)
        self._origin = time.perf_counter()

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _add_root(self, span: Span) -> None:
        with self._lock:
            self._roots.append(span)

    # ---------------------------------------------------------------- API
    def span(self, name: str, metric: Histogram | None = None,
             labels: dict | None = None, **attrs):
        """A new active span, or the shared no-op when tracing is off."""
        if not _enabled:
            return NULL_SPAN
        return Span(self, name, metric, labels, attrs)

    def current(self) -> Span | None:
        """The innermost open span on THIS thread (None outside spans)."""
        stack = self._stack()
        return stack[-1] if stack else None

    def roots(self) -> list[Span]:
        with self._lock:
            return list(self._roots)

    def clear(self) -> None:
        with self._lock:
            self._roots.clear()

    def to_dicts(self) -> list[dict]:
        return [r.to_dict() for r in self.roots()]

    def to_json(self) -> str:
        return json.dumps(self.to_dicts())

    def chrome_trace(self) -> list[dict]:
        """Finished spans as Chrome trace-event 'X' (complete) events —
        load via chrome://tracing or https://ui.perfetto.dev."""
        pid = os.getpid()
        events: list[dict] = []

        def emit(span: Span) -> None:
            events.append({
                "name": span.name,
                "ph": "X",
                "ts": (span.start - self._origin) * 1e6,
                "dur": (span.duration or 0.0) * 1e6,
                "pid": pid,
                "tid": span.tid,
                "args": dict(span.attrs),
            })
            for c in span.children:
                emit(c)

        for root in self.roots():
            emit(root)
        return events


#: the process-global tracer (pairs with metrics.REGISTRY)
TRACER = Tracer()


def span(name: str, metric: Histogram | None = None,
         labels: dict | None = None, **attrs):
    """Module-level convenience for ``TRACER.span`` (the common call)."""
    return TRACER.span(name, metric=metric, labels=labels, **attrs)


def current_span() -> Span | None:
    return TRACER.current()


def roots() -> list[Span]:
    return TRACER.roots()


def clear() -> None:
    TRACER.clear()


def to_dicts() -> list[dict]:
    return TRACER.to_dicts()


def to_json() -> str:
    return TRACER.to_json()


def chrome_trace() -> list[dict]:
    return TRACER.chrome_trace()
