"""Remote monitoring poster (reference: common/monitoring_api, 574 LoC
— periodically POSTs beaconnode/validator process metrics JSON to a
remote endpoint in the beaconcha.in client-stats format) plus the
psutil-free process self-observation the health governor feeds on:
an RSS reader (``/proc/self/status`` VmRSS with a
``resource.getrusage`` fallback) behind the
``process_resident_memory_bytes`` gauge, and a jit-cache entry
estimate behind ``bls_jit_cache_entries``."""

from __future__ import annotations

import json
import threading
import time
import urllib.request

from .metrics import REGISTRY

RSS_BYTES = REGISTRY.gauge(
    "process_resident_memory_bytes",
    "Resident set size of this process (VmRSS; getrusage fallback)",
)
JIT_CACHE_ENTRIES = REGISTRY.gauge(
    "bls_jit_cache_entries",
    "Estimated live jit-cache entries (compiles since last counted clear)",
)
JIT_CACHE_CLEARS = REGISTRY.counter(
    "bls_jit_cache_clears_total",
    "Counted jax.clear_caches() invocations, by cause",
    ("cause",),
)


def read_rss_bytes() -> int:
    """Current RSS in bytes without psutil: ``/proc/self/status``
    VmRSS (kB) where procfs exists, else ``resource.getrusage``
    ru_maxrss (kB on Linux — a high-water mark, still monotone enough
    for the leak sentinel). Returns 0 only if both fail."""
    try:
        with open("/proc/self/status", "rb") as fh:
            for line in fh:
                if line.startswith(b"VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:  # lhtpu: ignore[LH502] -- resource module absent off-unix; 0 means RSS unknown
        return 0


def sample_rss() -> int:
    """Read RSS and mirror it into ``process_resident_memory_bytes``."""
    rss = read_rss_bytes()
    RSS_BYTES.set(rss)
    return rss


# Jit-cache entry accounting: JAX exposes no stable global cache-size
# API, so we count compiles (jax_backend's jit-cache probe calls
# note_jit_compile on every miss) and re-baseline on a counted clear.
_JIT_LOCK = threading.Lock()
_JIT_COMPILES = 0
_JIT_BASELINE = 0


def note_jit_compile(n: int = 1) -> None:
    """A jit-cache miss (a compile) happened; bump the entry estimate."""
    global _JIT_COMPILES
    with _JIT_LOCK:
        _JIT_COMPILES += n
        JIT_CACHE_ENTRIES.set(_JIT_COMPILES - _JIT_BASELINE)


def note_jit_cache_cleared(cause: str = "manual") -> None:
    """The caches were dropped (jax.clear_caches / arena prune):
    re-baseline the entry estimate and count the clear."""
    global _JIT_BASELINE
    with _JIT_LOCK:
        _JIT_BASELINE = _JIT_COMPILES
        JIT_CACHE_ENTRIES.set(0)
    JIT_CACHE_CLEARS.inc(cause=cause)


def jit_cache_entry_count() -> int:
    """Estimated live jit-cache entries since the last counted clear."""
    with _JIT_LOCK:
        return _JIT_COMPILES - _JIT_BASELINE


class MonitoringService:
    def __init__(self, endpoint: str, node=None, vc=None, timeout: float = 5.0):
        self.endpoint = endpoint
        self.node = node
        self.vc = vc
        self.timeout = timeout
        self.posts = 0

    def collect(self) -> list[dict]:
        """client-stats JSON bodies (monitoring_api/src/types.rs)."""
        now = int(time.time() * 1000)
        out = []
        if self.node is not None:
            chain = self.node.chain
            head = chain.head()
            out.append(
                {
                    "version": 1,
                    "timestamp": now,
                    "process": "beaconnode",
                    "sync_beacon_head_slot": int(head.block.message.slot),
                    "sync_eth2_synced": (
                        chain.current_slot()
                        - int(head.block.message.slot)
                    ) <= 1,
                    "slasher_active": self.node.slasher is not None,
                    "network_peers_connected": (
                        len(self.node.network.peer_manager.connected_peers())
                        if self.node.network
                        else 0
                    ),
                }
            )
        if self.vc is not None:
            out.append(
                {
                    "version": 1,
                    "timestamp": now,
                    "process": "validator",
                    "validator_total": len(self.vc.store.voting_pubkeys()),
                    "validator_active": len(self.vc.store.voting_pubkeys()),
                }
            )
        return out

    def post(self) -> bool:
        body = json.dumps(self.collect()).encode()
        req = urllib.request.Request(
            self.endpoint,
            data=body,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout):
                self.posts += 1
                return True
        except OSError:
            return False
