"""Remote monitoring poster (reference: common/monitoring_api, 574 LoC
— periodically POSTs beaconnode/validator process metrics JSON to a
remote endpoint in the beaconcha.in client-stats format)."""

from __future__ import annotations

import json
import time
import urllib.request


class MonitoringService:
    def __init__(self, endpoint: str, node=None, vc=None, timeout: float = 5.0):
        self.endpoint = endpoint
        self.node = node
        self.vc = vc
        self.timeout = timeout
        self.posts = 0

    def collect(self) -> list[dict]:
        """client-stats JSON bodies (monitoring_api/src/types.rs)."""
        now = int(time.time() * 1000)
        out = []
        if self.node is not None:
            chain = self.node.chain
            head = chain.head()
            out.append(
                {
                    "version": 1,
                    "timestamp": now,
                    "process": "beaconnode",
                    "sync_beacon_head_slot": int(head.block.message.slot),
                    "sync_eth2_synced": (
                        chain.current_slot()
                        - int(head.block.message.slot)
                    ) <= 1,
                    "slasher_active": self.node.slasher is not None,
                    "network_peers_connected": (
                        len(self.node.network.peer_manager.connected_peers())
                        if self.node.network
                        else 0
                    ),
                }
            )
        if self.vc is not None:
            out.append(
                {
                    "version": 1,
                    "timestamp": now,
                    "process": "validator",
                    "validator_total": len(self.vc.store.voting_pubkeys()),
                    "validator_active": len(self.vc.store.voting_pubkeys()),
                }
            )
        return out

    def post(self) -> bool:
        body = json.dumps(self.collect()).encode()
        req = urllib.request.Request(
            self.endpoint,
            data=body,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout):
                self.posts += 1
                return True
        except OSError:
            return False
