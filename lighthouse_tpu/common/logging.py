"""Structured logging (reference: common/logging — slog terminal/file
formatting, test_logger, and metrics on log counts).

slog-style key-value structured records over the stdlib logging core:
``log.info("Block imported", slot=5, root="0x…")`` renders as the
reference's `INFO Block imported, slot: 5, root: 0x…` terminal format.
A global counter per level feeds the metrics registry exactly like the
reference counts log lines.
"""

from __future__ import annotations

import sys
import time

from .metrics import REGISTRY

_LOG_COUNTS = None


def _counts():
    global _LOG_COUNTS
    if _LOG_COUNTS is None:
        _LOG_COUNTS = REGISTRY.counter(
            "log_messages_total", "Log lines emitted", ("level",)
        )
    return _LOG_COUNTS


class StructuredLogger:
    LEVELS = {"debug": 10, "info": 20, "warn": 30, "error": 40, "crit": 50}

    def __init__(self, name: str = "lighthouse_tpu", level: str = "info",
                 stream=None, fields: dict | None = None):
        self.name = name
        self.level = self.LEVELS[level]
        self.stream = stream if stream is not None else sys.stderr
        self.fields = dict(fields or {})

    def bind(self, **fields) -> "StructuredLogger":
        """Child logger with extra context (slog's o!())."""
        merged = dict(self.fields)
        merged.update(fields)
        return StructuredLogger(
            self.name, "debug", self.stream, merged
        )._with_level(self.level)

    def _with_level(self, level: int) -> "StructuredLogger":
        self.level = level
        return self

    def _log(self, level_name: str, msg: str, kv: dict) -> None:
        if self.LEVELS[level_name] < self.level:
            return
        _counts().inc(level=level_name)
        merged = dict(self.fields)
        merged.update(kv)
        suffix = "".join(f", {k}: {v}" for k, v in merged.items())
        ts = time.strftime("%b %d %H:%M:%S")
        self.stream.write(
            f"{ts} {level_name.upper():5s} {msg}{suffix}\n"
        )

    def debug(self, msg, **kv):
        self._log("debug", msg, kv)

    def info(self, msg, **kv):
        self._log("info", msg, kv)

    def warn(self, msg, **kv):
        self._log("warn", msg, kv)

    def error(self, msg, **kv):
        self._log("error", msg, kv)

    def crit(self, msg, **kv):
        self._log("crit", msg, kv)


class NullLogger(StructuredLogger):
    """Discard everything (the reference's NullLoggerConfig for tests)."""

    def __init__(self):
        super().__init__(level="crit")

    def _log(self, *a, **k):
        pass


def test_logger() -> StructuredLogger:
    """Logger for tests: visible only when pytest shows output."""
    return StructuredLogger(level="debug", stream=sys.stdout)
