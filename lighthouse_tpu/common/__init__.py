"""Cross-cutting commons (reference: common/*)."""

from . import resilience, tracing  # noqa: F401
from .logging import NullLogger, StructuredLogger, test_logger  # noqa: F401
from .metrics import REGISTRY, Counter, Gauge, Histogram, Registry  # noqa: F401
from .slot_clock import ManualSlotClock, SlotClock, SystemSlotClock  # noqa: F401
from .support import (  # noqa: F401
    Fallback,
    FallbackError,
    HashSetDelay,
    Lockfile,
    LockfileError,
    LRUTimeCache,
    SensitiveUrl,
)
from .task_executor import ShutdownSignal, TaskExecutor  # noqa: F401
