"""Fault-tolerance primitives for the BLS dispatch hot path.

Three of five bench rounds lost their official number to *transient*
infrastructure faults, not wrong math: r05 died inside ``hash_to_g2``
with ``remote_compile: response body closed before all bytes were
read``, r03 to a one-shot ``Unable to initialize backend 'axon'`` init
race, r04 to a Mosaic lowering error on an untested default. The
reference client survives exactly this class of failure through its
execution-layer retry/fallback discipline (``execution_layer``'s
engine fallback + ``Fallback::first_success``; SURVEY §5/§7.3: "keep a
host CPU fallback path"). This module is that discipline for the
device dispatch path, built from four pieces:

* :func:`classify` — splits an exception into *transient* (tunnel /
  socket resets, remote_compile body drops, backend-init races,
  deadline hits: retry is likely to succeed) vs *permanent* (Mosaic
  lowering errors, shape mismatches, correctness asserts: retrying is
  wasted budget, degrade instead), plus a ``kind`` label for metrics.
* :class:`RetryPolicy` — bounded exponential backoff with jitter;
  :func:`call_with_retries` applies it to any callable.
* :class:`CircuitBreaker` — closed → open → half-open per dispatch
  rung (``fused`` → ``classic`` → ``native``), mirrored into the
  ``bls_breaker_state`` gauge. Permanent failures trip straight to
  open; transients accumulate to the threshold. Half-open admits one
  probe; its outcome closes or re-opens.
* :class:`FaultInjector` — deterministic fault injection from
  ``LHTPU_FAULT_INJECT=<stage>:<kind>:<count>`` (comma-separable), so
  every rung of the degradation ladder is exercisable in CI without a
  TPU. Kinds raise the *real* error strings of the r03/r05 incidents,
  so the injection exercises the same classifier path production hits.

Plus :func:`force_with_deadline`, the guard against hangs rather than
errors: a wedged device transfer becomes a classified transient
``DeadlineExceeded`` with stage attribution instead of eating the
bench watchdog budget (deadline-in-a-worker-thread, the same
surface-don't-deadlock discipline as ``common/timeout_lock.py``).

Env knobs (declared in :mod:`lighthouse_tpu.common.knobs`, all read at
call time, not import time — the PR 1 trace-time convention — except
breaker threshold/cooldown, read when a breaker is (re)created, i.e. at
import or :func:`reset`): ``LHTPU_RESILIENCE``, ``LHTPU_RETRY_MAX``,
``LHTPU_RETRY_BASE_MS``, ``LHTPU_RETRY_CAP_MS``, ``LHTPU_RETRY_JITTER``,
``LHTPU_RETRY_SEED``, ``LHTPU_BREAKER_THRESHOLD``,
``LHTPU_BREAKER_COOLDOWN_S``, ``LHTPU_SYNC_DEADLINE_S``,
``LHTPU_FAULT_INJECT``, ``LHTPU_FAULT_HANG_S`` — see the registry (or
README's knob table) for defaults and semantics.
"""

from __future__ import annotations

import random
import sys
import threading
import time

from . import knobs
from .metrics import REGISTRY

TRANSIENT = "transient"
PERMANENT = "permanent"

#: the degradation ladder, best rung first (jax_backend walks it)
LADDER = ("fused", "classic", "native")

# breaker states (the bls_breaker_state gauge values)
CLOSED, OPEN, HALF_OPEN = 0, 1, 2
_STATE_NAMES = {CLOSED: "closed", OPEN: "open", HALF_OPEN: "half-open"}


RETRIES_TOTAL = REGISTRY.counter(
    "bls_dispatch_retries_total",
    "Transient-fault retries inside BLS dispatch, by stage and fault kind",
    ("stage", "kind"),
)
BREAKER_STATE = REGISTRY.gauge(
    "bls_breaker_state",
    "Dispatch-rung circuit breaker state (0=closed, 1=open, 2=half-open)",
    ("path",),
)
DEGRADED_TOTAL = REGISTRY.counter(
    "bls_degraded_dispatches_total",
    "Verifications answered by a rung below the configured dispatch path",
    ("path",),
)
FAULTS_INJECTED = REGISTRY.counter(
    "bls_faults_injected_total",
    "Deterministic faults fired by LHTPU_FAULT_INJECT",
    ("stage", "kind"),
)
DEADLINE_TOTAL = REGISTRY.counter(
    "bls_deadline_exceeded_total",
    "Deadline-bounded operations that hit their deadline",
    ("stage",),
)
BREAKER_TRANSITIONS = REGISTRY.counter(
    "bls_breaker_transitions_total",
    "Breaker state changes by rung and destination state "
    "(the gauge only shows the latest state; flapping needs the counter)",
    ("rung", "to"),
)


def enabled() -> bool:
    """Retry + degradation ladder on? (``LHTPU_RESILIENCE=0`` restores
    the raw raise-through behavior; read per call.)"""
    return bool(knobs.knob("LHTPU_RESILIENCE"))


class DeadlineExceeded(TimeoutError):
    """A deadline-bounded operation (device_sync force) hit its
    deadline — a wedged transfer surfaced as a classified transient
    instead of an indefinite hang."""


class BatchPreempted(InterruptedError):
    """A coalesced batch lost its dispatch window to a higher class
    (the continuous scheduler preempting aggregates/attestations for a
    block). Transient by construction: the abandoned events re-enqueue
    at the front of their lanes exactly once and re-dispatch after the
    preempting work — any layer that observes the abort must retry in
    place, never degrade a rung or count a verdict."""


# --------------------------------------------------------------- classifier

# Message substrings (lowercased match) -> retry-worthiness. PERMANENT
# patterns are checked FIRST: a compile error that happens to mention
# "unavailable" must not be retried forever. The transient table is
# seeded with the literal r03/r05 failure strings.
_PERMANENT_PATTERNS: tuple[tuple[str, str], ...] = (
    ("unimplemented primitive", "lowering"),
    ("mosaic", "lowering"),
    ("pallas", "lowering"),
    ("not implemented", "lowering"),
    ("invalid argument", "invalid"),
    ("invalid_argument", "invalid"),
    ("incompatible shapes", "shape"),
    ("resource_exhausted", "oom"),
    ("resource exhausted", "oom"),
    ("out of memory", "oom"),
    # A chip dropping out of the mesh mid-serve: the sharded program is
    # unrunnable until the mesh is rebuilt — permanent for THIS topology
    # (the sharded breaker degrades dispatch to single-chip; half-open
    # re-promotion probes the mesh after the cooldown).
    ("device lost", "chip_loss"),
    ("chip removed from mesh", "chip_loss"),
)
_TRANSIENT_PATTERNS: tuple[tuple[str, str], ...] = (
    # The r05 bench-killer family: the PJRT proxy's HTTP body truncated
    # mid-read ("remote_compile: read body: response body closed before
    # all bytes were read", BENCH_r05.json). Seeded broadly — any
    # "read body" / "closed before all bytes" truncation is the same
    # droppable-response shape, whichever endpoint the proxy names.
    ("remote_compile", "remote_compile"),        # r05
    ("response body closed", "remote_compile"),  # r05
    ("read body", "remote_compile"),             # r05 family
    ("closed before all bytes", "remote_compile"),  # r05 family
    ("unable to initialize backend", "backend_init"),  # r03
    ("backend setup/compile error", "backend_init"),   # r03
    ("connection reset", "socket"),
    ("connection refused", "socket"),
    ("connection aborted", "socket"),
    ("broken pipe", "socket"),
    ("socket", "socket"),
    ("tunnel", "socket"),
    ("unexpected eof", "socket"),
    ("deadline exceeded", "hang"),
    ("deadline_exceeded", "hang"),
    ("timed out", "timeout"),
    ("timeout", "timeout"),
    ("unavailable", "unavailable"),
    ("temporarily", "unavailable"),
    ("try again", "unavailable"),
)
# Exception types whose class alone decides. Correctness-shaped types
# are permanent no matter the message (an AssertionError mentioning
# "timeout" is still a correctness assert).
_PERMANENT_TYPES = (
    NotImplementedError, AssertionError, TypeError, ValueError,
    KeyError, IndexError, AttributeError, ArithmeticError,
)
_TRANSIENT_TYPES = (ConnectionError, TimeoutError, InterruptedError, OSError)


def classify(exc: BaseException) -> tuple[str, str]:
    """(category, kind) for an exception: category is
    :data:`TRANSIENT` or :data:`PERMANENT`; kind is the metrics label
    (``remote_compile`` / ``backend_init`` / ``socket`` / ``hang`` /
    ``timeout`` / ``unavailable`` / ``lowering`` / ...). Unrecognized
    errors default to permanent: a wasted retry is cheap, but an
    unbounded retry of a correctness bug would mask it — the ladder
    still rescues the verdict."""
    if isinstance(exc, DeadlineExceeded):
        return TRANSIENT, "hang"
    if isinstance(exc, BatchPreempted):
        return TRANSIENT, "preempted"
    msg = f"{type(exc).__name__}: {exc}".lower()
    if isinstance(exc, _PERMANENT_TYPES):
        for pattern, kind in _PERMANENT_PATTERNS:
            if pattern in msg:
                return PERMANENT, kind
        return PERMANENT, type(exc).__name__
    for pattern, kind in _PERMANENT_PATTERNS:
        if pattern in msg:
            return PERMANENT, kind
    for pattern, kind in _TRANSIENT_PATTERNS:
        if pattern in msg:
            return TRANSIENT, kind
    if isinstance(exc, TimeoutError):
        return TRANSIENT, "timeout"
    if isinstance(exc, _TRANSIENT_TYPES):
        return TRANSIENT, "socket"
    return PERMANENT, "unclassified"


def is_transient(exc: BaseException) -> bool:
    return classify(exc)[0] == TRANSIENT


# ------------------------------------------------------------- retry policy

_JITTER_RNG = random.Random()
_JITTER_SEED_SEEN: str | None = None


def _jitter_rng() -> random.Random:
    """The module jitter RNG, re-seeded whenever LHTPU_RETRY_SEED
    changes (deterministic backoff schedules for tests/drills)."""
    global _JITTER_SEED_SEEN
    seed = knobs.knob("LHTPU_RETRY_SEED")
    if seed != _JITTER_SEED_SEEN:
        _JITTER_SEED_SEEN = seed
        _JITTER_RNG.seed(None if seed is None else seed)
    return _JITTER_RNG


class RetryPolicy:
    """Bounded exponential backoff + jitter (reference:
    execution_layer's capped engine-retry schedule)."""

    def __init__(self, max_retries: int | None = None,
                 base_s: float | None = None, cap_s: float | None = None,
                 jitter: float | None = None):
        self.max_retries = (
            int(knobs.knob("LHTPU_RETRY_MAX")) if max_retries is None
            else max_retries
        )
        self.base_s = (
            knobs.knob("LHTPU_RETRY_BASE_MS") / 1e3 if base_s is None
            else base_s
        )
        self.cap_s = (
            knobs.knob("LHTPU_RETRY_CAP_MS") / 1e3 if cap_s is None
            else cap_s
        )
        self.jitter = (
            knobs.knob("LHTPU_RETRY_JITTER") if jitter is None
            else jitter
        )

    def backoff(self, attempt: int) -> float:
        """Sleep before retry ``attempt`` (1-based): base * 2^(n-1),
        capped, plus up to ``jitter`` fraction on top (decorrelates
        herds of retries against a recovering tunnel)."""
        delay = min(self.cap_s, self.base_s * (2 ** (attempt - 1)))
        if self.jitter > 0 and delay > 0:
            delay *= 1.0 + self.jitter * _jitter_rng().random()
        return delay

    def sleep(self, attempt: int) -> None:
        delay = self.backoff(attempt)
        if delay > 0:
            time.sleep(delay)


def retry_policy() -> RetryPolicy:
    """A policy from the current env (read per call)."""
    return RetryPolicy()


def call_with_retries(fn, stage: str, policy: RetryPolicy | None = None):
    """Run ``fn`` retrying transient failures per ``policy``; permanent
    failures and exhausted budgets re-raise. Every retry lands in
    ``bls_dispatch_retries_total{stage,kind}``."""
    if not enabled():
        return fn()
    if policy is None:
        policy = retry_policy()
    attempt = 0
    while True:
        try:
            return fn()
        except Exception as exc:
            category, kind = classify(exc)
            if category != TRANSIENT or attempt >= policy.max_retries:
                raise
            attempt += 1
            RETRIES_TOTAL.inc(stage=stage, kind=kind)
            policy.sleep(attempt)


# ----------------------------------------------------------- circuit breaker


class CircuitBreaker:
    """closed → open → half-open breaker for one dispatch rung.

    * closed: all calls allowed; ``threshold`` consecutive failures
      (or ONE permanent failure — a lowering bug will not heal) open it.
    * open: calls refused until ``cooldown_s`` elapses, then half-open.
    * half-open: exactly one probe admitted; success closes, failure
      re-opens (and re-arms the cooldown).

    State mirrors into ``bls_breaker_state{path=...}`` (0/1/2).
    ``clock`` is injectable for deterministic tests.
    """

    def __init__(self, name: str, threshold: int | None = None,
                 cooldown_s: float | None = None, clock=time.monotonic):
        self.name = name
        self.threshold = (
            int(knobs.knob("LHTPU_BREAKER_THRESHOLD")) if threshold is None
            else threshold
        )
        self.cooldown_s = (
            knobs.knob("LHTPU_BREAKER_COOLDOWN_S") if cooldown_s is None
            else cooldown_s
        )
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False
        BREAKER_STATE.set(CLOSED, path=name)

    @property
    def state(self) -> int:
        return self._state

    @property
    def state_name(self) -> str:
        return _STATE_NAMES[self._state]

    def _set(self, state: int) -> None:
        if state != self._state:
            BREAKER_TRANSITIONS.inc(rung=self.name, to=_STATE_NAMES[state])
        self._state = state
        BREAKER_STATE.set(state, path=self.name)

    def allow(self) -> bool:
        """May a call go through this rung right now? (open → half-open
        transition happens here once the cooldown has elapsed.)"""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at < self.cooldown_s:
                    return False
                self._set(HALF_OPEN)
                self._probing = True
                return True
            # HALF_OPEN: admit exactly one in-flight probe
            if self._probing:
                return False
            self._probing = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probing = False
            if self._state != CLOSED:
                self._set(CLOSED)

    def record_failure(self, permanent: bool = False) -> None:
        with self._lock:
            self._failures += 1
            was_probe = self._probing
            self._probing = False
            if permanent or was_probe or self._failures >= self.threshold:
                self._opened_at = self._clock()
                self._set(OPEN)

    def release(self) -> None:
        """Return an admitted-but-unused call slot: a half-open probe
        that its caller decided not to dispatch after all (e.g. the
        sharded planner admitted a dispatch whose retained packs turn
        out not to divide the mesh) must not wedge the breaker
        half-open with a phantom in-flight probe."""
        with self._lock:
            self._probing = False


_BREAKERS: dict[str, CircuitBreaker] = {}
_BREAKERS_LOCK = threading.Lock()


def breaker(path: str) -> CircuitBreaker:
    """The process-wide breaker for a dispatch rung (created on first
    use; env thresholds read then — :func:`reset` re-reads)."""
    with _BREAKERS_LOCK:
        br = _BREAKERS.get(path)
        if br is None:
            br = _BREAKERS[path] = CircuitBreaker(path)
        return br


def breaker_states() -> dict[str, str]:
    """{rung: state-name} for every ladder rung plus any extra breakers
    created on demand (e.g. the dispatch engine's "sharded" breaker —
    bench/report surface)."""
    with _BREAKERS_LOCK:
        extra = [p for p in _BREAKERS if p not in LADDER]
    return {
        path: breaker(path).state_name for path in (*LADDER, *extra)
    }


def breaker_transitions_total() -> float:
    """Sum of ``bls_breaker_transitions_total`` over every rung/state —
    the flap-rate sentinel and the soak's per-epoch delta read this."""
    return sum(v for _, v in BREAKER_TRANSITIONS.items())


# ------------------------------------------------------------ fault injection

# kind -> exception factory, seeded with the LITERAL r03/r05/r04 error
# strings so injected faults walk the same classifier path production
# faults do ([injected] marks them in logs).
_FAULT_FACTORIES = {
    "remote_compile": lambda: RuntimeError(
        "INTERNAL: http://127.0.0.1:8103/remote_compile: read body: "
        "response body closed before all bytes were read [injected]"
    ),
    "backend_init": lambda: RuntimeError(
        "Unable to initialize backend 'axon': UNAVAILABLE: TPU backend "
        "setup/compile error (Unavailable). [injected]"
    ),
    "socket": lambda: ConnectionResetError(
        "[Errno 104] Connection reset by peer [injected]"
    ),
    "unavailable": lambda: RuntimeError(
        "UNAVAILABLE: device tunnel dropped [injected]"
    ),
    "mosaic": lambda: NotImplementedError(
        "Unimplemented primitive in Pallas TPU lowering for "
        "KernelType.TC: dynamic_slice [injected]"
    ),
    "shape": lambda: TypeError(
        "incompatible shapes for dispatch operands [injected]"
    ),
    "assert": lambda: AssertionError("injected correctness assert"),
    "chip_loss": lambda: RuntimeError(
        "INTERNAL: Device lost: TPU chip removed from mesh "
        "(interconnect failure) [injected]"
    ),
    "preempted": lambda: BatchPreempted(
        "coalesced batch preempted by higher-class work [injected]"
    ),
}


class FaultInjector:
    """Deterministic stage-targeted faults from ``LHTPU_FAULT_INJECT``.

    Spec: ``stage:kind:count`` items, comma-separated; each matching
    :meth:`fire` consumes one count and raises the kind's exception
    (``hang`` sleeps ``LHTPU_FAULT_HANG_S`` instead — a wedge, not an
    error). The spec string is re-read every call; changing it resets
    the remaining counts, so one process can run a whole drill matrix.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._spec: str | None = None
        self._remaining: dict[tuple[str, str], int] = {}
        self._warned: set[str] = set()

    def _refresh_locked(self) -> None:
        spec = knobs.knob("LHTPU_FAULT_INJECT")
        if spec == self._spec:
            return
        self._spec = spec
        self._remaining = {}
        for item in filter(None, (p.strip() for p in spec.split(","))):
            try:
                stage, kind, count = item.split(":")
                self._remaining[(stage, kind)] = int(count)
            except ValueError:
                if item not in self._warned:
                    self._warned.add(item)
                    print(
                        f"resilience: ignoring malformed "
                        f"LHTPU_FAULT_INJECT item {item!r} "
                        f"(want stage:kind:count)",
                        file=sys.stderr,
                    )

    def fire(self, stage: str) -> None:
        """Raise (or hang) if the spec has a live fault for ``stage``;
        no-op otherwise. The fast path (no env) is one dict read."""
        if not knobs.knob("LHTPU_FAULT_INJECT"):
            if self._spec:
                with self._lock:
                    self._refresh_locked()
            return
        with self._lock:
            self._refresh_locked()
            kind = None
            for (st, kd), left in self._remaining.items():
                if st == stage and left > 0:
                    self._remaining[(st, kd)] = left - 1
                    kind = kd
                    break
            if kind is None:
                return
        FAULTS_INJECTED.inc(stage=stage, kind=kind)
        if kind == "hang":
            time.sleep(knobs.knob("LHTPU_FAULT_HANG_S"))
            return
        raise _FAULT_FACTORIES.get(
            kind, lambda: RuntimeError(f"injected fault: {kind}")
        )()

    def reset(self) -> None:
        with self._lock:
            self._spec = None
            self._remaining = {}


_INJECTOR = FaultInjector()


def maybe_inject(stage: str) -> None:
    """Fire a pending injected fault for ``stage`` (production no-op
    unless ``LHTPU_FAULT_INJECT`` is set)."""
    _INJECTOR.fire(stage)


def rearm_faults() -> None:
    """Re-arm ``LHTPU_FAULT_INJECT`` counts WITHOUT touching breaker
    state. The injector keeps exhausted counts while the spec string is
    unchanged (so one drill matrix can run in-process); a soak that
    schedules the same fault in consecutive epochs must re-arm at each
    epoch boundary to get that epoch's fresh fault budget."""
    _INJECTOR.reset()


# ------------------------------------------------------------------ deadline


def force_with_deadline(fn, stage: str = "device_sync",
                        deadline_s: float | None = None):
    """Run ``fn`` under a wall-clock deadline; on expiry raise
    :class:`DeadlineExceeded` (transient, kind=hang) with stage
    attribution instead of hanging into the bench watchdog.

    The callable runs in a daemon worker thread that is ABANDONED on
    expiry (a thread wedged inside a dead PJRT transfer cannot be
    cancelled — the caller's retry re-dispatches instead). Injected
    faults for ``stage`` fire inside the guarded region, so the
    ``hang`` kind exercises exactly this deadline. ``deadline_s`` <= 0
    runs inline (no thread, no guard)."""
    if deadline_s is None:
        deadline_s = knobs.knob("LHTPU_SYNC_DEADLINE_S")
    if deadline_s <= 0:
        maybe_inject(stage)
        return fn()
    box: dict = {}

    def run():
        try:
            maybe_inject(stage)
            box["value"] = fn()
        except BaseException as exc:  # surfaced on the caller thread
            box["error"] = exc

    worker = threading.Thread(
        target=run, daemon=True, name=f"lhtpu-{stage}-deadline"
    )
    worker.start()
    worker.join(deadline_s)
    if worker.is_alive():
        DEADLINE_TOTAL.inc(stage=stage)
        raise DeadlineExceeded(
            f"{stage} exceeded its {deadline_s}s deadline "
            f"(wedged device transfer?)"
        )
    if "error" in box:
        raise box["error"]
    return box.get("value")


# --------------------------------------------------------------------- reset


def reset() -> None:
    """Forget breaker state and pending injected faults; re-read breaker
    env knobs on next use. Test/drill isolation hook."""
    with _BREAKERS_LOCK:
        _BREAKERS.clear()
    for path in LADDER:
        breaker(path)  # re-create eagerly so /metrics always shows all rungs
    _INJECTOR.reset()


# Eagerly surface every rung's breaker (gauge=0) on the first scrape.
for _path in LADDER:
    breaker(_path)
del _path
