"""glibc allocator tuning + metrics.

Capability mirror of `common/malloc_utils` (src/lib.rs:1-30 + glibc.rs):
the reference caps glibc malloc arena count and trim/mmap thresholds at
startup (long-running beacon nodes otherwise accumulate per-thread
arenas and fragment), and scrapes ``mallinfo`` into metrics. Here the
same knobs are driven through ``mallopt(3)`` via ctypes; on non-glibc
platforms every call degrades to a no-op, like the reference's
conditional compilation.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import sys

# glibc mallopt parameter numbers (malloc.h)
M_MMAP_THRESHOLD = -3
M_ARENA_MAX = -8
M_TRIM_THRESHOLD = -1

# The reference (malloc_utils glibc.rs) sets only a 128 KiB mmap threshold.
# We additionally cap arenas at 4 and use 2 MiB mmap/trim thresholds: this
# process hosts large long-lived JAX host buffers (batch staging arrays)
# rather than many small tokio tasks, so fewer arenas + a higher mmap cutoff
# keep RSS stable without syscall-churning madvise on every batch.
DEFAULT_ARENA_MAX = 4
DEFAULT_MMAP_THRESHOLD = 2 * 1024 * 1024
DEFAULT_TRIM_THRESHOLD = 2 * 1024 * 1024

_libc = None


def _glibc():
    global _libc
    if _libc is None:
        if not sys.platform.startswith("linux"):
            _libc = False
        else:
            try:
                lib = ctypes.CDLL(ctypes.util.find_library("c") or "libc.so.6")
                lib.mallopt  # glibc only
                _libc = lib
            except (OSError, AttributeError):
                _libc = False
    return _libc or None


def configure_memory_allocator(
    arena_max: int = DEFAULT_ARENA_MAX,
    mmap_threshold: int = DEFAULT_MMAP_THRESHOLD,
    trim_threshold: int = DEFAULT_TRIM_THRESHOLD,
) -> bool:
    """Apply the allocator tuning; returns False on non-glibc (no-op)."""
    lib = _glibc()
    if lib is None:
        return False
    ok = True
    for param, value in (
        (M_ARENA_MAX, arena_max),
        (M_MMAP_THRESHOLD, mmap_threshold),
        (M_TRIM_THRESHOLD, trim_threshold),
    ):
        if value is not None and lib.mallopt(param, value) != 1:
            ok = False
    return ok


class _Mallinfo2(ctypes.Structure):
    _fields_ = [(name, ctypes.c_size_t) for name in (
        "arena", "ordblks", "smblks", "hblks", "hblkhd",
        "usmblks", "fsmblks", "uordblks", "fordblks", "keepcost",
    )]


def scrape_allocator_metrics() -> dict[str, int]:
    """mallinfo2 snapshot → metric dict (glibc.rs
    scrape_mallinfo_metrics); empty on non-glibc."""
    lib = _glibc()
    if lib is None:
        return {}
    try:
        fn = lib.mallinfo2
    except AttributeError:
        return {}
    fn.restype = _Mallinfo2
    info = fn()
    return {name: int(getattr(info, name)) for name, _ in _Mallinfo2._fields_}
