"""Small shared utilities."""


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 0; returns 1 for n <= 1)."""
    m = 1
    while m < n:
        m <<= 1
    return m
