"""Vector generation in the consensus-spec-tests layout (reference:
testing/state_transition_vectors — vectors generated FROM the harness
and asserted; here additionally written in the official directory
format so the ef_tests handlers are exercised end-to-end offline).

``generate_vectors(root)`` writes, under ``root/tests/``:

* general/phase0/bls/{sign,verify,aggregate,aggregate_verify,
  fast_aggregate_verify,eth_aggregate_pubkeys,eth_fast_aggregate_verify}
* minimal/phase0/shuffling/core
* minimal/phase0/operations/{attestation,voluntary_exit,block_header}
* minimal/phase0/sanity/{slots,blocks}
* minimal/phase0/epoch_processing/justification_and_finalization
* minimal/phase0/ssz_static/{Attestation,AttestationData,Checkpoint}

Valid AND invalid cases are emitted per runner (invalid = no post file
/ null output, per the official convention).
"""

from __future__ import annotations

import os

import yaml

from ..chain.harness import BeaconChainHarness
from ..consensus.shuffle import shuffle_indices
from ..crypto.bls.api import (
    AggregateSignature,
    SecretKey,
    aggregate_pubkeys,
)
from ..network import snappy


def _write(path: str, data: bytes) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        f.write(data)


def _write_ssz_snappy(path: str, raw: bytes) -> None:
    _write(path, snappy.compress(raw))


def _write_yaml(path: str, obj) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        yaml.safe_dump(obj, f)


def _case(root, config, fork, runner, handler, suite, name) -> str:
    return os.path.join(root, "tests", config, fork, runner, handler, suite, name)


# ------------------------------------------------------------------ BLS
def _gen_bls(root: str) -> None:
    sks = [SecretKey.from_int(i + 1) for i in range(4)]
    msg = b"\x12" * 32
    msg2 = b"\x34" * 32

    def bls_case(handler, name, inp, out):
        d = _case(root, "general", "phase0", "bls", handler, "bls", name)
        _write_yaml(os.path.join(d, "data.yaml"), {"input": inp, "output": out})

    # sign
    sig0 = sks[0].sign(msg)
    bls_case(
        "sign", "case_0",
        {"privkey": "0x" + sks[0].to_bytes().hex(), "message": "0x" + msg.hex()},
        "0x" + sig0.to_bytes().hex(),
    )
    bls_case(
        "sign", "case_zero_privkey",
        {"privkey": "0x" + "00" * 32, "message": "0x" + msg.hex()},
        None,
    )
    # verify
    pk0 = sks[0].public_key()
    bls_case(
        "verify", "case_valid",
        {"pubkey": "0x" + pk0.to_bytes().hex(), "message": "0x" + msg.hex(),
         "signature": "0x" + sig0.to_bytes().hex()},
        True,
    )
    bls_case(
        "verify", "case_wrong_message",
        {"pubkey": "0x" + pk0.to_bytes().hex(), "message": "0x" + msg2.hex(),
         "signature": "0x" + sig0.to_bytes().hex()},
        False,
    )
    bls_case(
        "verify", "case_infinity_pubkey",
        {"pubkey": "0x" + ("c0" + "00" * 47),
         "message": "0x" + msg.hex(),
         "signature": "0x" + ("c0" + "00" * 95)},
        False,
    )
    # aggregate
    sigs = [sk.sign(msg) for sk in sks[:3]]
    agg = AggregateSignature.aggregate(sigs)
    bls_case(
        "aggregate", "case_0",
        ["0x" + s.to_bytes().hex() for s in sigs],
        "0x" + agg.to_bytes().hex(),
    )
    bls_case("aggregate", "case_empty", [], None)
    # aggregate_verify (distinct messages)
    msgs = [bytes([i]) * 32 for i in range(3)]
    per = [sks[i].sign(msgs[i]) for i in range(3)]
    agg2 = AggregateSignature.aggregate(per)
    bls_case(
        "aggregate_verify", "case_valid",
        {"pubkeys": ["0x" + sks[i].public_key().to_bytes().hex() for i in range(3)],
         "messages": ["0x" + m.hex() for m in msgs],
         "signature": "0x" + agg2.to_bytes().hex()},
        True,
    )
    bls_case(
        "aggregate_verify", "case_tampered",
        {"pubkeys": ["0x" + sks[i].public_key().to_bytes().hex() for i in range(3)],
         "messages": ["0x" + m.hex() for m in reversed(msgs)],
         "signature": "0x" + agg2.to_bytes().hex()},
        False,
    )
    # fast_aggregate_verify (same message)
    agg3 = AggregateSignature.aggregate(sigs)
    bls_case(
        "fast_aggregate_verify", "case_valid",
        {"pubkeys": ["0x" + sk.public_key().to_bytes().hex() for sk in sks[:3]],
         "message": "0x" + msg.hex(),
         "signature": "0x" + agg3.to_bytes().hex()},
        True,
    )
    bls_case(
        "fast_aggregate_verify", "case_extra_pubkey",
        {"pubkeys": ["0x" + sk.public_key().to_bytes().hex() for sk in sks],
         "message": "0x" + msg.hex(),
         "signature": "0x" + agg3.to_bytes().hex()},
        False,
    )
    # eth_aggregate_pubkeys
    agg_pk = aggregate_pubkeys([sk.public_key() for sk in sks])
    bls_case(
        "eth_aggregate_pubkeys", "case_0",
        ["0x" + sk.public_key().to_bytes().hex() for sk in sks],
        "0x" + agg_pk.to_bytes().hex(),
    )
    bls_case("eth_aggregate_pubkeys", "case_empty", [], None)
    # eth_fast_aggregate_verify: infinity sig + no pubkeys is VALID
    bls_case(
        "eth_fast_aggregate_verify", "case_valid",
        {"pubkeys": ["0x" + sk.public_key().to_bytes().hex() for sk in sks[:3]],
         "message": "0x" + msg.hex(),
         "signature": "0x" + agg3.to_bytes().hex()},
        True,
    )
    bls_case(
        "eth_fast_aggregate_verify", "case_infinity_empty",
        {"pubkeys": [], "message": "0x" + msg.hex(),
         "signature": "0x" + ("c0" + "00" * 95)},
        True,
    )


# -------------------------------------------------------------- shuffling
def _gen_shuffling(root: str, spec) -> None:
    rounds = spec.preset.SHUFFLE_ROUND_COUNT
    for i, (seed, count) in enumerate(
        [(b"\x01" * 32, 8), (b"\x02" * 32, 33), (b"\xff" * 32, 1)]
    ):
        mapping = list(int(x) for x in shuffle_indices(count, seed, rounds))
        d = _case(root, "minimal", "phase0", "shuffling", "core", "shuffle", f"case_{i}")
        _write_yaml(
            os.path.join(d, "mapping.yaml"),
            {"seed": "0x" + seed.hex(), "count": count, "mapping": mapping},
        )


# ----------------------------------------------------- state-driven vectors
def _gen_state_vectors(root: str) -> None:
    h = BeaconChainHarness(validator_count=16, backend="python")
    spec = h.spec
    chain = h.chain

    # sanity/slots: advance 3 empty slots
    pre = chain.head().state.copy()
    from ..consensus.transition.slot import process_slots

    post = process_slots(pre.copy(), int(pre.slot) + 3, spec)
    d = _case(root, "minimal", "phase0", "sanity", "slots", "pyspec_tests", "slots_3")
    _write_ssz_snappy(os.path.join(d, "pre.ssz_snappy"), pre.encode())
    _write_yaml(os.path.join(d, "slots.yaml"), 3)
    _write_ssz_snappy(os.path.join(d, "post.ssz_snappy"), post.encode())

    # sanity/blocks: one real signed block (valid) + wrong-proposer (invalid)
    pre_block_state = chain.head().state.copy()
    slot = h.advance_slot()
    block = h.make_block(slot)
    root_ = chain.process_block(block)
    post_state = chain.head().state
    d = _case(root, "minimal", "phase0", "sanity", "blocks", "pyspec_tests", "valid_block")
    _write_ssz_snappy(os.path.join(d, "pre.ssz_snappy"), pre_block_state.encode())
    _write_yaml(os.path.join(d, "meta.yaml"), {"blocks_count": 1})
    _write_ssz_snappy(os.path.join(d, "blocks_0.ssz_snappy"), block.encode())
    _write_ssz_snappy(os.path.join(d, "post.ssz_snappy"), post_state.encode())

    bad = block.copy()
    bad.message.proposer_index = (int(block.message.proposer_index) + 1) % 16
    d = _case(root, "minimal", "phase0", "sanity", "blocks", "pyspec_tests", "invalid_proposer")
    _write_ssz_snappy(os.path.join(d, "pre.ssz_snappy"), pre_block_state.encode())
    _write_yaml(os.path.join(d, "meta.yaml"), {"blocks_count": 1})
    _write_ssz_snappy(os.path.join(d, "blocks_0.ssz_snappy"), bad.encode())
    # no post file = expected rejection

    # operations/attestation: valid + wrong-committee (invalid)
    atts = [v.attestation for v in h.attest(slot)]
    att = atts[0]
    att_pre = chain.head().state.copy()
    target = int(att.data.slot) + 1
    if int(att_pre.slot) < target:
        att_pre = process_slots(att_pre, target, spec)
    from ..consensus.transition.block import (
        SignatureStrategy,
        _registry_pubkey_provider,
        _SigCollector,
    )
    from ..consensus.transition import block as blk

    applied = att_pre.copy()
    col = _SigCollector(SignatureStrategy.VERIFY_INDIVIDUALLY, "python")
    blk.process_attestation(
        applied, att, spec, col, _registry_pubkey_provider(applied), {}
    )
    d = _case(root, "minimal", "phase0", "operations", "attestation", "pyspec_tests", "valid")
    _write_ssz_snappy(os.path.join(d, "pre.ssz_snappy"), att_pre.encode())
    _write_ssz_snappy(os.path.join(d, "attestation.ssz_snappy"), att.encode())
    _write_ssz_snappy(os.path.join(d, "post.ssz_snappy"), applied.encode())

    bad_att = att.copy()
    bad_att.data.index = 63  # committee index out of range
    d = _case(root, "minimal", "phase0", "operations", "attestation", "pyspec_tests", "invalid_index")
    _write_ssz_snappy(os.path.join(d, "pre.ssz_snappy"), att_pre.encode())
    _write_ssz_snappy(os.path.join(d, "attestation.ssz_snappy"), bad_att.encode())

    # epoch_processing/justification_and_finalization: from an epoch-end state
    h2 = BeaconChainHarness(validator_count=16)
    h2.extend_chain(2 * spec.preset.SLOTS_PER_EPOCH - 1)
    ep_pre = h2.chain.head().state.copy()
    boundary = (int(ep_pre.slot) // spec.preset.SLOTS_PER_EPOCH + 1) * (
        spec.preset.SLOTS_PER_EPOCH
    )
    ep_pre = process_slots(ep_pre, boundary - 1, spec)
    from ..consensus.transition import epoch as ep

    ep_post = ep_pre.copy()
    ep.process_justification_and_finalization_phase0(ep_post, spec)
    d = _case(
        root, "minimal", "phase0", "epoch_processing",
        "justification_and_finalization", "pyspec_tests", "case_0",
    )
    _write_ssz_snappy(os.path.join(d, "pre.ssz_snappy"), ep_pre.encode())
    _write_ssz_snappy(os.path.join(d, "post.ssz_snappy"), ep_post.encode())

    # ssz_static
    for name, obj in (
        ("Attestation", att),
        ("AttestationData", att.data),
        ("Checkpoint", att.data.target),
    ):
        d = _case(root, "minimal", "phase0", "ssz_static", name, "ssz_random", "case_0")
        _write_ssz_snappy(os.path.join(d, "serialized.ssz_snappy"), obj.encode())
        _write_yaml(
            os.path.join(d, "roots.yaml"),
            {"root": "0x" + obj.hash_tree_root().hex()},
        )

    # operations/voluntary_exit + block_header on a mature chain
    import dataclasses

    from ..consensus.config import MINIMAL, compute_signing_root, minimal_spec
    from ..consensus.types import SignedVoluntaryExit, VoluntaryExit

    especs = dataclasses.replace(
        minimal_spec(), preset=dataclasses.replace(MINIMAL, SHARD_COMMITTEE_PERIOD=0)
    )
    h3 = BeaconChainHarness(validator_count=16, backend="python", spec=especs)
    st = h3.chain.head().state
    exit_msg = VoluntaryExit(epoch=0, validator_index=2)
    domain = especs.get_domain(
        especs.DOMAIN_VOLUNTARY_EXIT, 0, st.fork, h3.chain.genesis_validators_root
    )
    signed_exit = SignedVoluntaryExit(
        message=exit_msg,
        signature=h3.keys[2].sign(compute_signing_root(exit_msg, domain)).to_bytes(),
    )
    applied = st.copy()
    col = _SigCollector(SignatureStrategy.VERIFY_INDIVIDUALLY, "python")
    blk.process_voluntary_exit(
        applied, signed_exit, especs, col, _registry_pubkey_provider(applied)
    )
    # NOTE: exit vectors use the zero-SHARD_COMMITTEE_PERIOD preset; the
    # handler derives its spec from the directory config, so these go
    # under a distinct config dir consumed only by our own runner setup.
    d = _case(root, "minimal_exitable", "phase0", "operations", "voluntary_exit", "pyspec_tests", "valid")
    _write_ssz_snappy(os.path.join(d, "pre.ssz_snappy"), st.encode())
    _write_ssz_snappy(os.path.join(d, "voluntary_exit.ssz_snappy"), signed_exit.encode())
    _write_ssz_snappy(os.path.join(d, "post.ssz_snappy"), applied.encode())


def _gen_fork_and_genesis(root: str) -> None:
    """fork/fork upgrade vectors + genesis initialization/validity
    (reference runners: fork, genesis)."""
    import dataclasses

    from ..consensus.config import minimal_spec
    from ..consensus.genesis import (
        genesis_deposits,
        initialize_beacon_state_from_eth1,
        interop_keypairs,
        is_valid_genesis_state,
    )
    from ..consensus.transition.upgrade import (
        upgrade_to_altair,
        upgrade_to_bellatrix,
    )

    spec = minimal_spec()
    genesis_spec = dataclasses.replace(
        spec, MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16
    )
    h = BeaconChainHarness(validator_count=16, backend="python")
    pre = h.chain.head().state.copy()

    # fork: phase0 -> altair, then altair -> bellatrix
    altair_spec = dataclasses.replace(spec, ALTAIR_FORK_EPOCH=0)
    post_a = upgrade_to_altair(pre.copy(), altair_spec)
    d = _case(root, "minimal", "altair", "fork", "fork", "pyspec_tests",
              "fork_base")
    _write_ssz_snappy(os.path.join(d, "pre.ssz_snappy"), pre.encode())
    _write_yaml(os.path.join(d, "meta.yaml"), {"fork": "altair"})
    _write_ssz_snappy(os.path.join(d, "post.ssz_snappy"), post_a.encode())

    merge_spec = dataclasses.replace(
        spec, ALTAIR_FORK_EPOCH=0, BELLATRIX_FORK_EPOCH=0
    )
    post_b = upgrade_to_bellatrix(post_a.copy(), merge_spec)
    d = _case(root, "minimal", "bellatrix", "fork", "fork", "pyspec_tests",
              "fork_base")
    _write_ssz_snappy(os.path.join(d, "pre.ssz_snappy"), post_a.encode())
    _write_yaml(os.path.join(d, "meta.yaml"), {"fork": "bellatrix"})
    _write_ssz_snappy(os.path.join(d, "post.ssz_snappy"), post_b.encode())

    # genesis/initialization: enough signed deposits for a valid genesis
    keys = interop_keypairs(16)
    deposits = genesis_deposits(
        keys, genesis_spec.preset.MAX_EFFECTIVE_BALANCE, genesis_spec,
        sign=True,
    )
    eth1_hash = b"\x42" * 32
    eth1_time = 1_606_824_000  # past MIN_GENESIS_TIME so the state is valid
    state = initialize_beacon_state_from_eth1(
        eth1_hash, eth1_time, deposits, genesis_spec
    )
    d = _case(root, "minimal_smallgenesis", "phase0", "genesis", "initialization",
              "pyspec_tests", "from_deposits")
    _write_yaml(os.path.join(d, "eth1.yaml"), {
        "eth1_block_hash": "0x" + eth1_hash.hex(),
        "eth1_timestamp": eth1_time,
    })
    _write_yaml(os.path.join(d, "meta.yaml"),
                {"deposits_count": len(deposits)})
    for i, dep in enumerate(deposits):
        _write_ssz_snappy(
            os.path.join(d, f"deposits_{i}.ssz_snappy"), dep.encode()
        )
    _write_ssz_snappy(os.path.join(d, "state.ssz_snappy"), state.encode())

    # genesis/validity: the state above is valid; an underfilled one isn't
    d = _case(root, "minimal_smallgenesis", "phase0", "genesis", "validity",
              "pyspec_tests", "valid")
    _write_ssz_snappy(os.path.join(d, "genesis.ssz_snappy"), state.encode())
    _write_yaml(os.path.join(d, "is_valid.yaml"), True)

    few = initialize_beacon_state_from_eth1(
        eth1_hash, eth1_time, deposits[:4], genesis_spec
    )
    assert not is_valid_genesis_state(few, genesis_spec)
    d = _case(root, "minimal_smallgenesis", "phase0", "genesis", "validity",
              "pyspec_tests", "too_few_validators")
    _write_ssz_snappy(os.path.join(d, "genesis.ssz_snappy"), few.encode())
    _write_yaml(os.path.join(d, "is_valid.yaml"), False)


def generate_vectors(root: str) -> int:
    """Write the full tree; returns number of case directories."""
    from ..consensus.config import minimal_spec

    _gen_bls(root)
    _gen_shuffling(root, minimal_spec())
    _gen_state_vectors(root)
    _gen_fork_and_genesis(root)
    count = 0
    for dirpath, dirnames, filenames in os.walk(os.path.join(root, "tests")):
        if filenames and not dirnames:
            count += 1
    return count
