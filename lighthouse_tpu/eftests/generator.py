"""Vector generation in the consensus-spec-tests layout (reference:
testing/state_transition_vectors — vectors generated FROM the harness
and asserted; here additionally written in the official directory
format so the ef_tests handlers are exercised end-to-end offline).

``generate_vectors(root)`` writes, under ``root/tests/``:

* general/phase0/bls/{sign,verify,aggregate,aggregate_verify,
  fast_aggregate_verify,eth_aggregate_pubkeys,eth_fast_aggregate_verify}
* minimal/phase0/shuffling/core
* minimal/phase0/operations/{attestation,voluntary_exit,block_header}
* minimal/phase0/sanity/{slots,blocks}
* minimal/phase0/epoch_processing/justification_and_finalization
* minimal/phase0/ssz_static/{Attestation,AttestationData,Checkpoint}

Valid AND invalid cases are emitted per runner (invalid = no post file
/ null output, per the official convention).
"""

from __future__ import annotations

import os

import yaml

from ..chain.harness import BeaconChainHarness
from ..consensus.shuffle import shuffle_indices
from ..crypto.bls.api import (
    AggregateSignature,
    SecretKey,
    aggregate_pubkeys,
)
from ..network import snappy


def _write(path: str, data: bytes) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        f.write(data)


def _write_ssz_snappy(path: str, raw: bytes) -> None:
    _write(path, snappy.compress(raw))


def _write_yaml(path: str, obj) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        yaml.safe_dump(obj, f)


def _case(root, config, fork, runner, handler, suite, name) -> str:
    return os.path.join(root, "tests", config, fork, runner, handler, suite, name)


# ------------------------------------------------------------------ BLS
def _gen_bls(root: str) -> None:
    sks = [SecretKey.from_int(i + 1) for i in range(4)]
    msg = b"\x12" * 32
    msg2 = b"\x34" * 32

    def bls_case(handler, name, inp, out):
        d = _case(root, "general", "phase0", "bls", handler, "bls", name)
        _write_yaml(os.path.join(d, "data.yaml"), {"input": inp, "output": out})

    # sign
    sig0 = sks[0].sign(msg)
    bls_case(
        "sign", "case_0",
        {"privkey": "0x" + sks[0].to_bytes().hex(), "message": "0x" + msg.hex()},
        "0x" + sig0.to_bytes().hex(),
    )
    bls_case(
        "sign", "case_zero_privkey",
        {"privkey": "0x" + "00" * 32, "message": "0x" + msg.hex()},
        None,
    )
    # verify
    pk0 = sks[0].public_key()
    bls_case(
        "verify", "case_valid",
        {"pubkey": "0x" + pk0.to_bytes().hex(), "message": "0x" + msg.hex(),
         "signature": "0x" + sig0.to_bytes().hex()},
        True,
    )
    bls_case(
        "verify", "case_wrong_message",
        {"pubkey": "0x" + pk0.to_bytes().hex(), "message": "0x" + msg2.hex(),
         "signature": "0x" + sig0.to_bytes().hex()},
        False,
    )
    bls_case(
        "verify", "case_infinity_pubkey",
        {"pubkey": "0x" + ("c0" + "00" * 47),
         "message": "0x" + msg.hex(),
         "signature": "0x" + ("c0" + "00" * 95)},
        False,
    )
    # aggregate
    sigs = [sk.sign(msg) for sk in sks[:3]]
    agg = AggregateSignature.aggregate(sigs)
    bls_case(
        "aggregate", "case_0",
        ["0x" + s.to_bytes().hex() for s in sigs],
        "0x" + agg.to_bytes().hex(),
    )
    bls_case("aggregate", "case_empty", [], None)
    # aggregate_verify (distinct messages)
    msgs = [bytes([i]) * 32 for i in range(3)]
    per = [sks[i].sign(msgs[i]) for i in range(3)]
    agg2 = AggregateSignature.aggregate(per)
    bls_case(
        "aggregate_verify", "case_valid",
        {"pubkeys": ["0x" + sks[i].public_key().to_bytes().hex() for i in range(3)],
         "messages": ["0x" + m.hex() for m in msgs],
         "signature": "0x" + agg2.to_bytes().hex()},
        True,
    )
    bls_case(
        "aggregate_verify", "case_tampered",
        {"pubkeys": ["0x" + sks[i].public_key().to_bytes().hex() for i in range(3)],
         "messages": ["0x" + m.hex() for m in reversed(msgs)],
         "signature": "0x" + agg2.to_bytes().hex()},
        False,
    )
    # fast_aggregate_verify (same message)
    agg3 = AggregateSignature.aggregate(sigs)
    bls_case(
        "fast_aggregate_verify", "case_valid",
        {"pubkeys": ["0x" + sk.public_key().to_bytes().hex() for sk in sks[:3]],
         "message": "0x" + msg.hex(),
         "signature": "0x" + agg3.to_bytes().hex()},
        True,
    )
    bls_case(
        "fast_aggregate_verify", "case_extra_pubkey",
        {"pubkeys": ["0x" + sk.public_key().to_bytes().hex() for sk in sks],
         "message": "0x" + msg.hex(),
         "signature": "0x" + agg3.to_bytes().hex()},
        False,
    )
    # eth_aggregate_pubkeys
    agg_pk = aggregate_pubkeys([sk.public_key() for sk in sks])
    bls_case(
        "eth_aggregate_pubkeys", "case_0",
        ["0x" + sk.public_key().to_bytes().hex() for sk in sks],
        "0x" + agg_pk.to_bytes().hex(),
    )
    bls_case("eth_aggregate_pubkeys", "case_empty", [], None)
    # eth_fast_aggregate_verify: infinity sig + no pubkeys is VALID
    bls_case(
        "eth_fast_aggregate_verify", "case_valid",
        {"pubkeys": ["0x" + sk.public_key().to_bytes().hex() for sk in sks[:3]],
         "message": "0x" + msg.hex(),
         "signature": "0x" + agg3.to_bytes().hex()},
        True,
    )
    bls_case(
        "eth_fast_aggregate_verify", "case_infinity_empty",
        {"pubkeys": [], "message": "0x" + msg.hex(),
         "signature": "0x" + ("c0" + "00" * 95)},
        True,
    )


# -------------------------------------------------------------- shuffling
def _gen_shuffling(root: str, spec) -> None:
    rounds = spec.preset.SHUFFLE_ROUND_COUNT
    for i, (seed, count) in enumerate(
        [(b"\x01" * 32, 8), (b"\x02" * 32, 33), (b"\xff" * 32, 1)]
    ):
        mapping = list(int(x) for x in shuffle_indices(count, seed, rounds))
        d = _case(root, "minimal", "phase0", "shuffling", "core", "shuffle", f"case_{i}")
        _write_yaml(
            os.path.join(d, "mapping.yaml"),
            {"seed": "0x" + seed.hex(), "count": count, "mapping": mapping},
        )


# ----------------------------------------------------- state-driven vectors
def _gen_state_vectors(root: str) -> None:
    h = BeaconChainHarness(validator_count=16, backend="python")
    spec = h.spec
    chain = h.chain

    # sanity/slots: advance 3 empty slots
    pre = chain.head().state.copy()
    from ..consensus.transition.slot import process_slots

    post = process_slots(pre.copy(), int(pre.slot) + 3, spec)
    d = _case(root, "minimal", "phase0", "sanity", "slots", "pyspec_tests", "slots_3")
    _write_ssz_snappy(os.path.join(d, "pre.ssz_snappy"), pre.encode())
    _write_yaml(os.path.join(d, "slots.yaml"), 3)
    _write_ssz_snappy(os.path.join(d, "post.ssz_snappy"), post.encode())

    # sanity/blocks: one real signed block (valid) + wrong-proposer (invalid)
    pre_block_state = chain.head().state.copy()
    slot = h.advance_slot()
    block = h.make_block(slot)
    root_ = chain.process_block(block)
    post_state = chain.head().state
    d = _case(root, "minimal", "phase0", "sanity", "blocks", "pyspec_tests", "valid_block")
    _write_ssz_snappy(os.path.join(d, "pre.ssz_snappy"), pre_block_state.encode())
    _write_yaml(os.path.join(d, "meta.yaml"), {"blocks_count": 1})
    _write_ssz_snappy(os.path.join(d, "blocks_0.ssz_snappy"), block.encode())
    _write_ssz_snappy(os.path.join(d, "post.ssz_snappy"), post_state.encode())

    bad = block.copy()
    bad.message.proposer_index = (int(block.message.proposer_index) + 1) % 16
    d = _case(root, "minimal", "phase0", "sanity", "blocks", "pyspec_tests", "invalid_proposer")
    _write_ssz_snappy(os.path.join(d, "pre.ssz_snappy"), pre_block_state.encode())
    _write_yaml(os.path.join(d, "meta.yaml"), {"blocks_count": 1})
    _write_ssz_snappy(os.path.join(d, "blocks_0.ssz_snappy"), bad.encode())
    # no post file = expected rejection

    # operations/attestation: valid + wrong-committee (invalid)
    atts = [v.attestation for v in h.attest(slot)]
    att = atts[0]
    att_pre = chain.head().state.copy()
    target = int(att.data.slot) + 1
    if int(att_pre.slot) < target:
        att_pre = process_slots(att_pre, target, spec)
    from ..consensus.transition.block import (
        SignatureStrategy,
        _registry_pubkey_provider,
        _SigCollector,
    )
    from ..consensus.transition import block as blk

    applied = att_pre.copy()
    col = _SigCollector(SignatureStrategy.VERIFY_INDIVIDUALLY, "python")
    blk.process_attestation(
        applied, att, spec, col, _registry_pubkey_provider(applied), {}
    )
    d = _case(root, "minimal", "phase0", "operations", "attestation", "pyspec_tests", "valid")
    _write_ssz_snappy(os.path.join(d, "pre.ssz_snappy"), att_pre.encode())
    _write_ssz_snappy(os.path.join(d, "attestation.ssz_snappy"), att.encode())
    _write_ssz_snappy(os.path.join(d, "post.ssz_snappy"), applied.encode())

    bad_att = att.copy()
    bad_att.data.index = 63  # committee index out of range
    d = _case(root, "minimal", "phase0", "operations", "attestation", "pyspec_tests", "invalid_index")
    _write_ssz_snappy(os.path.join(d, "pre.ssz_snappy"), att_pre.encode())
    _write_ssz_snappy(os.path.join(d, "attestation.ssz_snappy"), bad_att.encode())

    # epoch_processing/justification_and_finalization: from an epoch-end state
    h2 = BeaconChainHarness(validator_count=16)
    h2.extend_chain(2 * spec.preset.SLOTS_PER_EPOCH - 1)
    ep_pre = h2.chain.head().state.copy()
    boundary = (int(ep_pre.slot) // spec.preset.SLOTS_PER_EPOCH + 1) * (
        spec.preset.SLOTS_PER_EPOCH
    )
    ep_pre = process_slots(ep_pre, boundary - 1, spec)
    from ..consensus.transition import epoch as ep

    ep_post = ep_pre.copy()
    ep.process_justification_and_finalization_phase0(ep_post, spec)
    d = _case(
        root, "minimal", "phase0", "epoch_processing",
        "justification_and_finalization", "pyspec_tests", "case_0",
    )
    _write_ssz_snappy(os.path.join(d, "pre.ssz_snappy"), ep_pre.encode())
    _write_ssz_snappy(os.path.join(d, "post.ssz_snappy"), ep_post.encode())

    # ssz_static
    for name, obj in (
        ("Attestation", att),
        ("AttestationData", att.data),
        ("Checkpoint", att.data.target),
    ):
        d = _case(root, "minimal", "phase0", "ssz_static", name, "ssz_random", "case_0")
        _write_ssz_snappy(os.path.join(d, "serialized.ssz_snappy"), obj.encode())
        _write_yaml(
            os.path.join(d, "roots.yaml"),
            {"root": "0x" + obj.hash_tree_root().hex()},
        )

    # operations/voluntary_exit + block_header on a mature chain
    import dataclasses

    from ..consensus.config import MINIMAL, compute_signing_root, minimal_spec
    from ..consensus.types import SignedVoluntaryExit, VoluntaryExit

    especs = dataclasses.replace(
        minimal_spec(), preset=dataclasses.replace(MINIMAL, SHARD_COMMITTEE_PERIOD=0)
    )
    h3 = BeaconChainHarness(validator_count=16, backend="python", spec=especs)
    st = h3.chain.head().state
    exit_msg = VoluntaryExit(epoch=0, validator_index=2)
    domain = especs.get_domain(
        especs.DOMAIN_VOLUNTARY_EXIT, 0, st.fork, h3.chain.genesis_validators_root
    )
    signed_exit = SignedVoluntaryExit(
        message=exit_msg,
        signature=h3.keys[2].sign(compute_signing_root(exit_msg, domain)).to_bytes(),
    )
    applied = st.copy()
    col = _SigCollector(SignatureStrategy.VERIFY_INDIVIDUALLY, "python")
    blk.process_voluntary_exit(
        applied, signed_exit, especs, col, _registry_pubkey_provider(applied)
    )
    # NOTE: exit vectors use the zero-SHARD_COMMITTEE_PERIOD preset; the
    # handler derives its spec from the directory config, so these go
    # under a distinct config dir consumed only by our own runner setup.
    d = _case(root, "minimal_exitable", "phase0", "operations", "voluntary_exit", "pyspec_tests", "valid")
    _write_ssz_snappy(os.path.join(d, "pre.ssz_snappy"), st.encode())
    _write_ssz_snappy(os.path.join(d, "voluntary_exit.ssz_snappy"), signed_exit.encode())
    _write_ssz_snappy(os.path.join(d, "post.ssz_snappy"), applied.encode())


def _gen_fork_and_genesis(root: str) -> None:
    """fork/fork upgrade vectors + genesis initialization/validity
    (reference runners: fork, genesis)."""
    import dataclasses

    from ..consensus.config import minimal_spec
    from ..consensus.genesis import (
        genesis_deposits,
        initialize_beacon_state_from_eth1,
        interop_keypairs,
        is_valid_genesis_state,
    )
    from ..consensus.transition.upgrade import (
        upgrade_to_altair,
        upgrade_to_bellatrix,
    )

    spec = minimal_spec()
    genesis_spec = dataclasses.replace(
        spec, MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16
    )
    h = BeaconChainHarness(validator_count=16, backend="python")
    pre = h.chain.head().state.copy()

    # fork: phase0 -> altair, then altair -> bellatrix
    altair_spec = dataclasses.replace(spec, ALTAIR_FORK_EPOCH=0)
    post_a = upgrade_to_altair(pre.copy(), altair_spec)
    d = _case(root, "minimal", "altair", "fork", "fork", "pyspec_tests",
              "fork_base")
    _write_ssz_snappy(os.path.join(d, "pre.ssz_snappy"), pre.encode())
    _write_yaml(os.path.join(d, "meta.yaml"), {"fork": "altair"})
    _write_ssz_snappy(os.path.join(d, "post.ssz_snappy"), post_a.encode())

    merge_spec = dataclasses.replace(
        spec, ALTAIR_FORK_EPOCH=0, BELLATRIX_FORK_EPOCH=0
    )
    post_b = upgrade_to_bellatrix(post_a.copy(), merge_spec)
    d = _case(root, "minimal", "bellatrix", "fork", "fork", "pyspec_tests",
              "fork_base")
    _write_ssz_snappy(os.path.join(d, "pre.ssz_snappy"), post_a.encode())
    _write_yaml(os.path.join(d, "meta.yaml"), {"fork": "bellatrix"})
    _write_ssz_snappy(os.path.join(d, "post.ssz_snappy"), post_b.encode())

    # genesis/initialization: enough signed deposits for a valid genesis
    keys = interop_keypairs(16)
    deposits = genesis_deposits(
        keys, genesis_spec.preset.MAX_EFFECTIVE_BALANCE, genesis_spec,
        sign=True,
    )
    eth1_hash = b"\x42" * 32
    eth1_time = 1_606_824_000  # past MIN_GENESIS_TIME so the state is valid
    state = initialize_beacon_state_from_eth1(
        eth1_hash, eth1_time, deposits, genesis_spec
    )
    d = _case(root, "minimal_smallgenesis", "phase0", "genesis", "initialization",
              "pyspec_tests", "from_deposits")
    _write_yaml(os.path.join(d, "eth1.yaml"), {
        "eth1_block_hash": "0x" + eth1_hash.hex(),
        "eth1_timestamp": eth1_time,
    })
    _write_yaml(os.path.join(d, "meta.yaml"),
                {"deposits_count": len(deposits)})
    for i, dep in enumerate(deposits):
        _write_ssz_snappy(
            os.path.join(d, f"deposits_{i}.ssz_snappy"), dep.encode()
        )
    _write_ssz_snappy(os.path.join(d, "state.ssz_snappy"), state.encode())

    # genesis/validity: the state above is valid; an underfilled one isn't
    d = _case(root, "minimal_smallgenesis", "phase0", "genesis", "validity",
              "pyspec_tests", "valid")
    _write_ssz_snappy(os.path.join(d, "genesis.ssz_snappy"), state.encode())
    _write_yaml(os.path.join(d, "is_valid.yaml"), True)

    few = initialize_beacon_state_from_eth1(
        eth1_hash, eth1_time, deposits[:4], genesis_spec
    )
    assert not is_valid_genesis_state(few, genesis_spec)
    d = _case(root, "minimal_smallgenesis", "phase0", "genesis", "validity",
              "pyspec_tests", "too_few_validators")
    _write_ssz_snappy(os.path.join(d, "genesis.ssz_snappy"), few.encode())
    _write_yaml(os.path.join(d, "is_valid.yaml"), False)


def _gen_epoch_and_rewards(root: str) -> None:
    """Every epoch_processing sub-transition + the rewards component
    deltas, phase0 and altair (reference runners: epoch_processing,
    rewards)."""
    import dataclasses

    from ..consensus.config import minimal_spec
    from ..consensus.transition import epoch as ep
    from ..consensus.transition.rewards import (
        attestation_deltas_altair,
        attestation_deltas_phase0,
    )
    from ..consensus.transition.slot import process_slots
    from ..consensus.transition.upgrade import upgrade_to_altair
    from .handlers import EpochProcessing, _deltas_container

    Deltas = _deltas_container()
    spec = minimal_spec()
    h = BeaconChainHarness(validator_count=16, backend="python")
    h.extend_chain(2 * spec.preset.SLOTS_PER_EPOCH - 1)
    base = h.chain.head().state.copy()
    boundary = (
        int(base.slot) // spec.preset.SLOTS_PER_EPOCH + 1
    ) * spec.preset.SLOTS_PER_EPOCH
    p0 = process_slots(base, boundary - 1, spec)

    altair_spec = dataclasses.replace(spec, ALTAIR_FORK_EPOCH=0)
    a0 = upgrade_to_altair(p0.copy(), altair_spec)

    _P0_ONLY = {"participation_record_updates"}
    _ALTAIR_ONLY = {
        "inactivity_updates", "participation_flag_updates",
        "sync_committee_updates",
    }

    def run_sub(state, sub, fork, sp):
        post = state.copy()
        if sub == "justification_and_finalization":
            if fork == "phase0":
                ep.process_justification_and_finalization_phase0(post, sp)
            else:
                ep.process_justification_and_finalization_altair(post, sp)
        elif sub == "rewards_and_penalties":
            if fork == "phase0":
                ep.process_rewards_and_penalties_phase0(post, sp)
            else:
                ep.process_rewards_and_penalties_altair(post, sp)
        elif sub == "participation_record_updates":
            ep.process_participation_record_updates(post)
        else:
            getattr(ep, f"process_{sub}")(post, sp)
        return post

    for fork, state, sp in (("phase0", p0, spec), ("altair", a0, altair_spec)):
        for sub in EpochProcessing.SUBS:
            if fork == "phase0" and sub in _ALTAIR_ONLY:
                continue
            if fork == "altair" and sub in _P0_ONLY:
                continue
            post = run_sub(state, sub, fork, sp)
            d = _case(root, "minimal", fork, "epoch_processing", sub,
                      "pyspec_tests", "case_0")
            _write_ssz_snappy(os.path.join(d, "pre.ssz_snappy"), state.encode())
            _write_ssz_snappy(os.path.join(d, "post.ssz_snappy"), post.encode())

        deltas = (
            attestation_deltas_phase0(state, sp)
            if fork == "phase0"
            else attestation_deltas_altair(state, sp)
        )
        d = _case(root, "minimal", fork, "rewards", "basic",
                  "pyspec_tests", "case_0")
        _write_ssz_snappy(os.path.join(d, "pre.ssz_snappy"), state.encode())
        for name, (rewards, penalties) in deltas.items():
            obj = Deltas(rewards=rewards, penalties=penalties)
            _write_ssz_snappy(
                os.path.join(d, f"{name}_deltas.ssz_snappy"), obj.encode()
            )


def _gen_transition(root: str) -> None:
    """Blocks crossing the phase0 -> altair boundary (reference runner:
    transition)."""
    import dataclasses

    from ..consensus.config import minimal_spec

    spec = dataclasses.replace(minimal_spec(), ALTAIR_FORK_EPOCH=1)
    h = BeaconChainHarness(validator_count=16, backend="python", spec=spec)
    pre = h.chain.head().state.copy()
    epoch_slots = spec.preset.SLOTS_PER_EPOCH
    blocks = []
    for _ in range(epoch_slots + 2):  # cross the epoch-1 boundary
        slot = h.advance_slot()
        block = h.make_block(slot)
        h.chain.process_block(block)
        blocks.append(block)
    fork_block = sum(
        1 for b in blocks if int(b.message.slot) < epoch_slots
    ) - 1
    d = _case(root, "minimal", "altair", "transition", "core",
              "pyspec_tests", "simple_transition")
    _write_ssz_snappy(os.path.join(d, "pre.ssz_snappy"), pre.encode())
    _write_yaml(os.path.join(d, "meta.yaml"), {
        "post_fork": "altair",
        "fork_epoch": 1,
        "blocks_count": len(blocks),
        "fork_block": fork_block,
    })
    for i, b in enumerate(blocks):
        _write_ssz_snappy(os.path.join(d, f"blocks_{i}.ssz_snappy"), b.encode())
    _write_ssz_snappy(
        os.path.join(d, "post.ssz_snappy"), h.chain.head().state.encode()
    )


def _gen_fork_choice(root: str) -> None:
    """Step-driven fork-choice vectors from a harness chain (reference
    runner: fork_choice/{get_head,on_block})."""
    from ..consensus.types import spec_types

    h = BeaconChainHarness(validator_count=16, backend="python")
    spec = h.spec
    t = spec_types(spec.preset)
    anchor_state = h.chain.head().state.copy()
    anchor_block = h.chain.head().block.message  # the genesis block

    genesis_time = int(anchor_state.genesis_time)
    steps = []
    blocks = []
    for _ in range(3):
        slot = h.advance_slot()
        block = h.make_block(slot)
        h.chain.process_block(block)
        blocks.append(block)
        steps.append({"tick": genesis_time + slot * spec.SECONDS_PER_SLOT})
        steps.append({"block": f"block_{len(blocks) - 1}"})
    head = h.chain.head()
    steps.append({
        "checks": {
            "head": {
                "slot": int(head.block.message.slot),
                "root": "0x" + head.root.hex(),
            }
        }
    })

    def write(case, extra_steps, sub):
        d = _case(root, "minimal", "phase0", "fork_choice", sub,
                  "pyspec_tests", case)
        _write_ssz_snappy(
            os.path.join(d, "anchor_state.ssz_snappy"), anchor_state.encode()
        )
        _write_ssz_snappy(
            os.path.join(d, "anchor_block.ssz_snappy"), anchor_block.encode()
        )
        for i, b in enumerate(blocks):
            _write_ssz_snappy(
                os.path.join(d, f"block_{i}.ssz_snappy"), b.encode()
            )
        _write_yaml(os.path.join(d, "steps.yaml"), extra_steps)

    write("chain_of_blocks", steps, "get_head")

    # on_block: a block whose slot is ahead of the tick must be rejected.
    future = [
        {"tick": genesis_time},  # time stays at genesis
        {"block": "block_0", "valid": False},
    ]
    write("future_block", future, "on_block")


def _gen_ssz_generic(root: str) -> None:
    """ssz_generic valid/invalid vectors named per the official
    conventions (reference runner: ssz_generic)."""
    from ..consensus.ssz import Bitlist, Bitvector, Boolean, Uint, Vector
    from .handlers import _ssz_test_container

    def write(handler, suite, name, raw, schema=None, value=None):
        d = _case(root, "general", "phase0", "ssz_generic", handler,
                  suite, name)
        _write_ssz_snappy(os.path.join(d, "serialized.ssz_snappy"), raw)
        if suite == "valid":
            root_hex = (
                value.hash_tree_root()
                if hasattr(value, "hash_tree_root")
                else schema.hash_tree_root(value)
            ).hex()
            _write_yaml(os.path.join(d, "meta.yaml"), {"root": "0x" + root_hex})

    # uints
    for bits, v in ((8, 0x7F), (16, 0xABCD), (32, 0xDEADBEEF),
                    (64, 2**63 + 17), (128, 2**100 + 5), (256, 2**200 + 9)):
        sch = Uint(bits // 8)
        write("uints", "valid", f"uint_{bits}_random", sch.encode(v),
              sch, v)
        write("uints", "invalid", f"uint_{bits}_one_byte_longer",
              sch.encode(v) + b"\x00")
    # boolean
    write("boolean", "valid", "true", b"\x01", Boolean(), True)
    write("boolean", "valid", "false", b"\x00", Boolean(), False)
    write("boolean", "invalid", "byte_2", b"\x02")
    # basic_vector
    sch = Vector(Uint(8), 64)
    v = [3] * 64
    write("basic_vector", "valid", "vec_uint64_64_filled",
          Vector(Uint(8), 64).encode(v), sch, v)
    write("basic_vector", "invalid", "vec_uint64_64_one_less",
          Vector(Uint(8), 64).encode(v)[:-8])
    # bitvector
    sch = Bitvector(9)
    bv = [True, False] * 4 + [True]
    write("bitvector", "valid", "bitvec_9_random", sch.encode(bv), sch, bv)
    write("bitvector", "invalid", "bitvec_9_extra_bit",
          bytes([0xFF, 0xFF]))  # bit above length 9 set
    # bitlist
    sch = Bitlist(8)
    bl = [True, True, False, True]
    write("bitlist", "valid", "bitlist_8_random", sch.encode(bl), sch, bl)
    write("bitlist", "invalid", "bitlist_8_no_delimiter", b"\x00")
    # containers
    for name, kwargs in (
        ("SingleFieldTestStruct", {"A": 0xAB}),
        ("SmallTestStruct", {"A": 0x1122, "B": 0x3344}),
        ("FixedTestStruct", {"A": 7, "B": 2**40, "C": 0xDDCCBBAA}),
        ("VarTestStruct", {"A": 45, "B": [1, 2, 3], "C": 9}),
        ("BitsStruct", {
            "A": [True, False, True],
            "B": [True, True],
            "C": [False],
            "D": [True] * 6,
            "E": [False, True] * 4,
        }),
    ):
        cls = _ssz_test_container(name)
        obj = cls(**kwargs)
        write("containers", "valid", f"{name}_valid", obj.encode(),
              value=obj)
        write("containers", "invalid", f"{name}_truncated",
              obj.encode()[:-1] if len(obj.encode()) > 1 else b"")


def _gen_ssz_static_breadth(root: str) -> None:
    """One vector per spec container the ssz_static runner names
    (reference runner: ssz_static over every type)."""
    import dataclasses

    from ..consensus import types as ct
    from ..consensus.config import minimal_spec
    from ..consensus.types import spec_types

    h = BeaconChainHarness(validator_count=16, backend="python")
    spec = h.spec
    t = spec_types(spec.preset)
    slot = h.advance_slot()
    block = h.make_block(slot)
    h.chain.process_block(block)
    atts = [v.attestation for v in h.attest(slot)]
    att = atts[0]
    state = h.chain.head().state

    indexed = __import__(
        "lighthouse_tpu.consensus.helpers", fromlist=["get_indexed_attestation"]
    ).get_indexed_attestation(state, att, spec)

    def T(name):
        # preset-parameterized containers live on the spec_types bundle;
        # preset-independent ones at module level
        return getattr(t, name, None) or getattr(ct, name)

    objs = {
        "Attestation": att,
        "AttestationData": att.data,
        "AttesterSlashing": T("AttesterSlashing")(
            attestation_1=indexed, attestation_2=indexed
        ),
        "BeaconBlockHeader": T("BeaconBlockHeader")(
            slot=1, proposer_index=2, parent_root=b"\x01" * 32,
            state_root=b"\x02" * 32, body_root=b"\x03" * 32,
        ),
        "Checkpoint": att.data.target,
        "DepositData": T("DepositData")(
            pubkey=b"\x11" * 48, withdrawal_credentials=b"\x22" * 32,
            amount=32 * 10**9, signature=b"\x33" * 96,
        ),
        "DepositMessage": T("DepositMessage")(
            pubkey=b"\x11" * 48, withdrawal_credentials=b"\x22" * 32,
            amount=32 * 10**9,
        ),
        "Eth1Data": state.eth1_data,
        "Fork": state.fork,
        "ForkData": T("ForkData")(
            current_version=b"\x00\x00\x00\x01",
            genesis_validators_root=b"\x42" * 32,
        ),
        "IndexedAttestation": indexed,
        "PendingAttestation": T("PendingAttestation")(
            aggregation_bits=att.aggregation_bits, data=att.data,
            inclusion_delay=1, proposer_index=0,
        ),
        "SignedBeaconBlockHeader": T("SignedBeaconBlockHeader")(
            message=T("BeaconBlockHeader")(
                slot=1, proposer_index=2, parent_root=b"\x01" * 32,
                state_root=b"\x02" * 32, body_root=b"\x03" * 32,
            ),
            signature=b"\x44" * 96,
        ),
        "SigningData": T("SigningData")(
            object_root=b"\x55" * 32, domain=b"\x66" * 32
        ),
        "Validator": state.validators[0],
        "VoluntaryExit": T("VoluntaryExit")(epoch=3, validator_index=4),
        "SignedVoluntaryExit": T("SignedVoluntaryExit")(
            message=T("VoluntaryExit")(epoch=3, validator_index=4),
            signature=b"\x77" * 96,
        ),
    }
    # Deposit carries a Vector[Bytes32, 33] proof.
    objs["Deposit"] = T("Deposit")(
        proof=[bytes([i]) * 32 for i in range(33)], data=objs["DepositData"]
    )
    # ProposerSlashing from two signed headers.
    objs["ProposerSlashing"] = T("ProposerSlashing")(
        signed_header_1=objs["SignedBeaconBlockHeader"],
        signed_header_2=objs["SignedBeaconBlockHeader"],
    )
    objs["HistoricalBatch"] = t.HistoricalBatch(
        block_roots=list(state.block_roots), state_roots=list(state.state_roots)
    )
    for name, obj in objs.items():
        d = _case(root, "minimal", "phase0", "ssz_static", name,
                  "ssz_random", "case_0")
        _write_ssz_snappy(os.path.join(d, "serialized.ssz_snappy"), obj.encode())
        _write_yaml(os.path.join(d, "roots.yaml"),
                    {"root": "0x" + obj.hash_tree_root().hex()})

    # altair/bellatrix containers under their fork dirs
    sync_agg = t.SyncAggregate(
        sync_committee_bits=[True] * spec.preset.SYNC_COMMITTEE_SIZE,
        sync_committee_signature=b"\x88" * 96,
    )
    sync_comm = t.SyncCommittee(
        pubkeys=[b"\x11" * 48] * spec.preset.SYNC_COMMITTEE_SIZE,
        aggregate_pubkey=b"\x11" * 48,
    )
    for name, obj in (("SyncAggregate", sync_agg), ("SyncCommittee", sync_comm)):
        d = _case(root, "minimal", "altair", "ssz_static", name,
                  "ssz_random", "case_0")
        _write_ssz_snappy(os.path.join(d, "serialized.ssz_snappy"), obj.encode())
        _write_yaml(os.path.join(d, "roots.yaml"),
                    {"root": "0x" + obj.hash_tree_root().hex()})

    payload = t.ExecutionPayload(
        parent_hash=b"\x01" * 32, fee_recipient=b"\x02" * 20,
        state_root=b"\x03" * 32, receipts_root=b"\x04" * 32,
        logs_bloom=b"\x00" * 256, prev_randao=b"\x05" * 32,
        block_number=7, gas_limit=30_000_000, gas_used=21_000,
        timestamp=12, extra_data=b"hi", base_fee_per_gas=10**9,
        block_hash=b"\x06" * 32, transactions=[b"\xaa\xbb"],
    )
    header_fields = {
        k: getattr(payload, k)
        for k in t.ExecutionPayloadHeader.fields
        if k != "transactions_root"
    }
    tx_schema = t.ExecutionPayload.fields["transactions"]
    header = t.ExecutionPayloadHeader(
        **header_fields,
        transactions_root=tx_schema.hash_tree_root(payload.transactions),
    )
    for name, obj in (
        ("ExecutionPayload", payload), ("ExecutionPayloadHeader", header),
    ):
        d = _case(root, "minimal", "bellatrix", "ssz_static", name,
                  "ssz_random", "case_0")
        _write_ssz_snappy(os.path.join(d, "serialized.ssz_snappy"), obj.encode())
        _write_yaml(os.path.join(d, "roots.yaml"),
                    {"root": "0x" + obj.hash_tree_root().hex()})


def _gen_execution_payload_op(root: str) -> None:
    """operations/execution_payload vectors on a pre-merge bellatrix
    state (reference: operations.rs execution_payload)."""
    import dataclasses

    from ..consensus import helpers as ch
    from ..consensus.config import minimal_spec
    from ..consensus.transition.block import (
        compute_timestamp_at_slot,
        process_execution_payload,
    )
    from ..consensus.transition.upgrade import (
        upgrade_to_altair,
        upgrade_to_bellatrix,
    )
    from ..consensus.types import spec_types

    spec = dataclasses.replace(
        minimal_spec(), ALTAIR_FORK_EPOCH=0, BELLATRIX_FORK_EPOCH=0
    )
    t = spec_types(spec.preset)
    h = BeaconChainHarness(validator_count=16, backend="python")
    state = upgrade_to_bellatrix(
        upgrade_to_altair(h.chain.head().state.copy(), spec), spec
    )

    randao = ch.get_randao_mix(
        state, ch.get_current_epoch(state, spec), spec
    )
    payload = t.ExecutionPayload(
        parent_hash=b"\x01" * 32, fee_recipient=b"\x02" * 20,
        state_root=b"\x03" * 32, receipts_root=b"\x04" * 32,
        logs_bloom=b"\x00" * 256, prev_randao=bytes(randao),
        block_number=1, gas_limit=30_000_000, gas_used=0,
        timestamp=compute_timestamp_at_slot(state, int(state.slot), spec),
        extra_data=b"", base_fee_per_gas=10**9,
        block_hash=b"\x06" * 32, transactions=[],
    )
    post = state.copy()
    process_execution_payload(post, payload, spec)

    d = _case(root, "minimal", "bellatrix", "operations",
              "execution_payload", "pyspec_tests", "valid")
    _write_ssz_snappy(os.path.join(d, "pre.ssz_snappy"), state.encode())
    _write_ssz_snappy(
        os.path.join(d, "execution_payload.ssz_snappy"), payload.encode()
    )
    _write_yaml(os.path.join(d, "execution.yaml"), {"execution_valid": True})
    _write_ssz_snappy(os.path.join(d, "post.ssz_snappy"), post.encode())

    bad = payload.copy()
    bad.timestamp = int(payload.timestamp) + 1
    d = _case(root, "minimal", "bellatrix", "operations",
              "execution_payload", "pyspec_tests", "invalid_timestamp")
    _write_ssz_snappy(os.path.join(d, "pre.ssz_snappy"), state.encode())
    _write_ssz_snappy(
        os.path.join(d, "execution_payload.ssz_snappy"), bad.encode()
    )
    _write_yaml(os.path.join(d, "execution.yaml"), {"execution_valid": True})

    d = _case(root, "minimal", "bellatrix", "operations",
              "execution_payload", "pyspec_tests", "engine_rejects")
    _write_ssz_snappy(os.path.join(d, "pre.ssz_snappy"), state.encode())
    _write_ssz_snappy(
        os.path.join(d, "execution_payload.ssz_snappy"), payload.encode()
    )
    _write_yaml(os.path.join(d, "execution.yaml"), {"execution_valid": False})


def generate_vectors(root: str) -> int:
    """Write the full tree; returns number of case directories."""
    from ..consensus.config import minimal_spec

    _gen_bls(root)
    _gen_shuffling(root, minimal_spec())
    _gen_state_vectors(root)
    _gen_fork_and_genesis(root)
    _gen_epoch_and_rewards(root)
    _gen_transition(root)
    _gen_fork_choice(root)
    _gen_ssz_generic(root)
    _gen_ssz_static_breadth(root)
    _gen_execution_payload_op(root)
    count = 0
    for dirpath, dirnames, filenames in os.walk(os.path.join(root, "tests")):
        if filenames and not dirnames:
            count += 1
    return count
