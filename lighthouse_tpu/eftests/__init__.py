"""Spec-conformance test rig (reference: testing/ef_tests, 4.6k LoC).

The reference data-drives `Handler`s over the official
consensus-spec-tests tarballs (v1.1.10): one handler per runner
(bls_*, shuffling, operations, sanity, epoch_processing, ssz_static,
finality…), each walking
``tests/<config>/<fork>/<runner>/<handler>/<suite>/<case>/`` and
comparing results file-by-file, with a coverage guard asserting no
vector was silently skipped (check_all_files_accessed.py).

This package reproduces that machinery byte-compatibly:

* ``handlers``  — the Handler registry, walking the same directory
  layout, reading the same file names (pre/post.ssz_snappy, meta.yaml,
  blocks_*.ssz_snappy, data.yaml) with our ssz + snappy codecs;
* ``generator`` — produces vector trees in the official layout from
  this implementation (the reference's testing/state_transition_vectors
  role), so the rig runs self-contained in this image; drop the
  official tarball at the same root and the identical handlers consume
  it for true cross-implementation conformance.
"""

from .handlers import CaseResult, Handler, run_all, run_handler
from .generator import generate_vectors

__all__ = [
    "CaseResult",
    "Handler",
    "generate_vectors",
    "run_all",
    "run_handler",
]
