"""Handlers over the consensus-spec-tests directory layout
(reference: testing/ef_tests/src/handler.rs:10-60 + cases/*.rs).

Each handler knows its runner name and how to execute one case
directory. ``run_handler`` walks
``<root>/tests/<config>/<fork>/<runner>/<handler>/<suite>/<case>`` and
returns per-case results; ``run_all`` additionally enforces the
coverage rule (every known runner present must run ≥1 case — the
check_all_files_accessed.py role).
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import yaml

from ..consensus.config import mainnet_spec, minimal_spec
from ..consensus.types import spec_types
from ..network import snappy


@dataclass
class CaseResult:
    handler: str
    case_path: str
    passed: bool
    message: str = ""


def _read(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def _read_ssz_snappy(path: str) -> bytes:
    return snappy.decompress(_read(path))


def _read_yaml(path: str):
    with open(path) as f:
        return yaml.safe_load(f)


def _spec_for(config: str, fork: str):
    import dataclasses

    if config == "minimal_exitable":
        # locally-generated exit vectors: minimal preset with
        # SHARD_COMMITTEE_PERIOD=0 so genesis validators may exit
        from ..consensus.config import MINIMAL

        spec = dataclasses.replace(
            minimal_spec(),
            preset=dataclasses.replace(MINIMAL, SHARD_COMMITTEE_PERIOD=0),
        )
    elif config == "minimal_smallgenesis":
        # locally-generated genesis vectors: 16 signed deposits are
        # enough to form a *valid* genesis under this config
        spec = dataclasses.replace(
            minimal_spec(), MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16
        )
    elif config in ("minimal", "general"):
        spec = minimal_spec()
    else:
        spec = mainnet_spec()

    if fork in ("altair", "bellatrix"):
        spec = dataclasses.replace(
            spec,
            ALTAIR_FORK_EPOCH=0,
            BELLATRIX_FORK_EPOCH=0 if fork == "bellatrix" else None,
        )
    return spec


def _state_cls(config: str, fork: str):
    t = spec_types(_spec_for(config, fork).preset)
    return {
        "phase0": t.BeaconStatePhase0,
        "altair": t.BeaconStateAltair,
        "bellatrix": t.BeaconStateBellatrix,
    }[fork]


class Handler:
    """Base: subclass sets runner/handler names + run_case."""

    runner: str
    handler: str

    def run_case(self, case_dir: str, config: str, fork: str) -> None:
        """Raise AssertionError (or any exception) to fail the case."""
        raise NotImplementedError


# --------------------------------------------------------------- BLS runner
class _BlsHandlerBase(Handler):
    runner = "bls"

    def _io(self, case_dir: str):
        data = _read_yaml(os.path.join(case_dir, "data.yaml"))
        return data["input"], data["output"]


def _hex(s: str) -> bytes:
    return bytes.fromhex(s.removeprefix("0x"))


class BlsSign(_BlsHandlerBase):
    handler = "sign"

    def run_case(self, case_dir, config, fork):
        from ..crypto.bls.api import BlsError, SecretKey

        inp, out = self._io(case_dir)
        try:
            sk = SecretKey.from_bytes(_hex(inp["privkey"]))
        except BlsError:
            assert out is None, "invalid privkey must yield null output"
            return
        sig = sk.sign(_hex(inp["message"]))
        if out is None:
            raise AssertionError("expected failure, got a signature")
        assert sig.to_bytes() == _hex(out)


class BlsVerify(_BlsHandlerBase):
    handler = "verify"

    def run_case(self, case_dir, config, fork):
        from ..crypto.bls.api import BlsError, PublicKey, Signature

        inp, expected = self._io(case_dir)
        try:
            pk = PublicKey.from_bytes(_hex(inp["pubkey"]))
            sig = Signature.from_bytes(_hex(inp["signature"]))
            ok = sig.verify(pk, _hex(inp["message"]))
        except BlsError:
            ok = False
        assert ok == expected


class BlsAggregate(_BlsHandlerBase):
    handler = "aggregate"

    def run_case(self, case_dir, config, fork):
        from ..crypto.bls.api import AggregateSignature, BlsError, Signature

        inp, out = self._io(case_dir)
        try:
            sigs = [Signature.from_bytes(_hex(s)) for s in inp]
            if not sigs:
                raise BlsError("empty aggregation")
            agg = AggregateSignature.aggregate(sigs)
        except BlsError:
            assert out is None
            return
        assert out is not None and agg.to_bytes() == _hex(out)


class BlsAggregateVerify(_BlsHandlerBase):
    handler = "aggregate_verify"

    def run_case(self, case_dir, config, fork):
        from ..crypto.bls.api import AggregateSignature, BlsError, PublicKey

        inp, expected = self._io(case_dir)
        try:
            pks = [PublicKey.from_bytes(_hex(p)) for p in inp["pubkeys"]]
            msgs = [_hex(m) for m in inp["messages"]]
            sig = AggregateSignature.from_bytes(_hex(inp["signature"]))
            ok = sig.aggregate_verify(pks, msgs)
        except BlsError:
            ok = False
        assert ok == expected


class BlsFastAggregateVerify(_BlsHandlerBase):
    handler = "fast_aggregate_verify"

    def run_case(self, case_dir, config, fork):
        from ..crypto.bls.api import AggregateSignature, BlsError, PublicKey

        inp, expected = self._io(case_dir)
        try:
            pks = [PublicKey.from_bytes(_hex(p)) for p in inp["pubkeys"]]
            sig = AggregateSignature.from_bytes(_hex(inp["signature"]))
            ok = sig.fast_aggregate_verify(pks, _hex(inp["message"]))
        except BlsError:
            ok = False
        assert ok == expected


class BlsEthAggregatePubkeys(_BlsHandlerBase):
    handler = "eth_aggregate_pubkeys"

    def run_case(self, case_dir, config, fork):
        from ..crypto.bls.api import BlsError, PublicKey, aggregate_pubkeys

        inp, out = self._io(case_dir)
        try:
            pks = [PublicKey.from_bytes(_hex(p)) for p in inp]
            agg = aggregate_pubkeys(pks)
        except BlsError:
            assert out is None
            return
        assert out is not None and agg.to_bytes() == _hex(out)


class BlsEthFastAggregateVerify(_BlsHandlerBase):
    handler = "eth_fast_aggregate_verify"

    def run_case(self, case_dir, config, fork):
        from ..crypto.bls.api import AggregateSignature, BlsError, PublicKey

        inp, expected = self._io(case_dir)
        try:
            pks = [PublicKey.from_bytes(_hex(p)) for p in inp["pubkeys"]]
            sig = AggregateSignature.from_bytes(_hex(inp["signature"]))
            ok = sig.eth_fast_aggregate_verify(pks, _hex(inp["message"]))
        except BlsError:
            ok = False
        assert ok == expected


# ---------------------------------------------------------- shuffling runner
class Shuffling(Handler):
    runner = "shuffling"
    handler = "core"

    def run_case(self, case_dir, config, fork):
        from ..consensus.shuffle import compute_shuffled_index, shuffle_indices

        data = _read_yaml(os.path.join(case_dir, "mapping.yaml"))
        seed = _hex(data["seed"])
        count = int(data["count"])
        expected = [int(x) for x in data["mapping"]]
        spec = _spec_for(config, fork)
        rounds = spec.preset.SHUFFLE_ROUND_COUNT
        if count:
            full = shuffle_indices(count, seed, rounds)
            assert list(full) == expected
        for i in range(min(count, 8)):
            assert compute_shuffled_index(i, count, seed, rounds) == expected[i]


# --------------------------------------------------------- operations runner
_OP_FILES = {
    "attestation": ("attestation.ssz_snappy", "Attestation"),
    "attester_slashing": ("attester_slashing.ssz_snappy", "AttesterSlashing"),
    "proposer_slashing": ("proposer_slashing.ssz_snappy", "ProposerSlashing"),
    "voluntary_exit": ("voluntary_exit.ssz_snappy", "SignedVoluntaryExit"),
    "deposit": ("deposit.ssz_snappy", "Deposit"),
    "block_header": ("block.ssz_snappy", None),
    "sync_aggregate": ("sync_aggregate.ssz_snappy", "SyncAggregate"),
    "execution_payload": ("execution_payload.ssz_snappy", "ExecutionPayload"),
}


class Operations(Handler):
    runner = "operations"

    def __init__(self, op_name: str):
        self.handler = op_name

    def run_case(self, case_dir, config, fork):
        from ..consensus.transition import block as blk
        from ..consensus.transition.block import (
            SignatureStrategy,
            _registry_pubkey_provider,
            _SigCollector,
        )

        spec = _spec_for(config, fork)
        t = spec_types(spec.preset)
        state_cls = _state_cls(config, fork)
        pre = state_cls.decode(_read_ssz_snappy(os.path.join(case_dir, "pre.ssz_snappy")))
        post_path = os.path.join(case_dir, "post.ssz_snappy")
        expect_success = os.path.exists(post_path)

        fname, type_name = _OP_FILES[self.handler]
        raw = _read_ssz_snappy(os.path.join(case_dir, fname))
        if self.handler == "block_header":
            op = t.BLOCK_BY_FORK[fork].decode(raw)
        else:
            from ..consensus import types as ct

            cls = getattr(t, type_name, None) or getattr(ct, type_name)
            op = cls.decode(raw)

        def apply():
            col = _SigCollector(SignatureStrategy.VERIFY_INDIVIDUALLY, None)
            get_pubkey = _registry_pubkey_provider(pre)
            if self.handler == "attestation":
                blk.process_attestation(pre, op, spec, col, get_pubkey, {})
            elif self.handler == "attester_slashing":
                blk.process_attester_slashing(pre, op, spec, col, get_pubkey)
            elif self.handler == "proposer_slashing":
                blk.process_proposer_slashing(pre, op, spec, col, get_pubkey)
            elif self.handler == "voluntary_exit":
                blk.process_voluntary_exit(pre, op, spec, col, get_pubkey)
            elif self.handler == "deposit":
                blk.process_deposit(pre, op, spec)
            elif self.handler == "block_header":
                blk.process_block_header(pre, op, spec)
            elif self.handler == "sync_aggregate":
                blk.process_sync_aggregate(pre, op, spec, col, get_pubkey)
            elif self.handler == "execution_payload":
                # execution.yaml carries the mocked engine verdict
                # (reference: operations.rs execution_payload handler).
                exe = _read_yaml(os.path.join(case_dir, "execution.yaml")) or {}
                valid = bool(exe.get("execution_valid", True))
                blk.process_execution_payload(
                    pre, op, spec, notify_new_payload=lambda _p: valid
                )
            col.finish()

        if expect_success:
            apply()
            post = state_cls.decode(_read_ssz_snappy(post_path))
            assert pre.hash_tree_root() == post.hash_tree_root(), "post-state mismatch"
        else:
            try:
                apply()
            except Exception:  # lhtpu: ignore[LH502] -- spec test expects rejection; ANY exception is the pass condition
                return
            raise AssertionError("expected operation to be rejected")


# ------------------------------------------------------------- sanity runner
class SanitySlots(Handler):
    runner = "sanity"
    handler = "slots"

    def run_case(self, case_dir, config, fork):
        from ..consensus.transition.slot import process_slots

        spec = _spec_for(config, fork)
        state_cls = _state_cls(config, fork)
        pre = state_cls.decode(_read_ssz_snappy(os.path.join(case_dir, "pre.ssz_snappy")))
        n = int(_read_yaml(os.path.join(case_dir, "slots.yaml")))
        post = state_cls.decode(_read_ssz_snappy(os.path.join(case_dir, "post.ssz_snappy")))
        out = process_slots(pre, int(pre.slot) + n, spec)
        assert out.hash_tree_root() == post.hash_tree_root()


class SanityBlocks(Handler):
    runner = "sanity"
    handler = "blocks"

    def run_case(self, case_dir, config, fork):
        from ..consensus.transition.block import (
            BlockProcessingError,
            SignatureStrategy,
            per_block_processing,
        )
        from ..consensus.transition.slot import process_slots

        spec = _spec_for(config, fork)
        t = spec_types(spec.preset)
        state_cls = _state_cls(config, fork)
        pre = state_cls.decode(_read_ssz_snappy(os.path.join(case_dir, "pre.ssz_snappy")))
        meta = _read_yaml(os.path.join(case_dir, "meta.yaml")) or {}
        count = int(meta.get("blocks_count", 1))
        post_path = os.path.join(case_dir, "post.ssz_snappy")
        expect_success = os.path.exists(post_path)

        state = pre

        def apply_all():
            nonlocal state
            for i in range(count):
                raw = _read_ssz_snappy(
                    os.path.join(case_dir, f"blocks_{i}.ssz_snappy")
                )
                block = t.SIGNED_BLOCK_BY_FORK[fork].decode(raw)
                if int(state.slot) < int(block.message.slot):
                    state = process_slots(state, int(block.message.slot), spec)
                per_block_processing(
                    state, block, spec,
                    strategy=SignatureStrategy.VERIFY_BULK,
                )
                if state.hash_tree_root() != bytes(block.message.state_root):
                    raise BlockProcessingError("state root mismatch")

        if expect_success:
            apply_all()
            post = state_cls.decode(_read_ssz_snappy(post_path))
            assert state.hash_tree_root() == post.hash_tree_root()
        else:
            try:
                apply_all()
            except Exception:  # lhtpu: ignore[LH502] -- spec test expects rejection; ANY exception is the pass condition
                return
            raise AssertionError("expected block to be rejected")


# ---------------------------------------------------- epoch processing runner
class EpochProcessing(Handler):
    runner = "epoch_processing"

    def __init__(self, sub: str):
        self.handler = sub

    # Every per-fork sub-transition the reference's epoch_processing
    # handler family covers (testing/ef_tests/src/cases/epoch_processing.rs).
    SUBS = (
        "justification_and_finalization", "rewards_and_penalties",
        "registry_updates", "slashings", "eth1_data_reset",
        "effective_balance_updates", "slashings_reset",
        "randao_mixes_reset", "historical_roots_update",
        "participation_record_updates",      # phase0 only
        "inactivity_updates",                # altair+
        "participation_flag_updates",        # altair+
        "sync_committee_updates",            # altair+
    )

    def run_case(self, case_dir, config, fork):
        from ..consensus.transition import epoch as ep

        spec = _spec_for(config, fork)
        state_cls = _state_cls(config, fork)
        pre = state_cls.decode(_read_ssz_snappy(os.path.join(case_dir, "pre.ssz_snappy")))
        post = state_cls.decode(_read_ssz_snappy(os.path.join(case_dir, "post.ssz_snappy")))
        h = self.handler
        if h == "justification_and_finalization":
            if fork == "phase0":
                ep.process_justification_and_finalization_phase0(pre, spec)
            else:
                ep.process_justification_and_finalization_altair(pre, spec)
        elif h == "rewards_and_penalties":
            if fork == "phase0":
                ep.process_rewards_and_penalties_phase0(pre, spec)
            else:
                ep.process_rewards_and_penalties_altair(pre, spec)
        elif h == "participation_record_updates":
            ep.process_participation_record_updates(pre)
        else:
            fn = {
                "registry_updates": ep.process_registry_updates,
                "slashings": ep.process_slashings,
                "eth1_data_reset": ep.process_eth1_data_reset,
                "effective_balance_updates": ep.process_effective_balance_updates,
                "slashings_reset": ep.process_slashings_reset,
                "randao_mixes_reset": ep.process_randao_mixes_reset,
                "historical_roots_update": ep.process_historical_roots_update,
                "inactivity_updates": ep.process_inactivity_updates,
                "participation_flag_updates": ep.process_participation_flag_updates,
                "sync_committee_updates": ep.process_sync_committee_updates,
            }[h]
            fn(pre, spec)
        assert pre.hash_tree_root() == post.hash_tree_root()


# ----------------------------------------------------------- ssz_static runner
class SszStatic(Handler):
    runner = "ssz_static"

    def __init__(self, type_name: str):
        self.handler = type_name

    def run_case(self, case_dir, config, fork):
        from ..consensus import types as ct

        t = spec_types(_spec_for(config, fork).preset)
        cls = getattr(t, self.handler, None) or getattr(ct, self.handler)
        serialized = _read_ssz_snappy(
            os.path.join(case_dir, "serialized.ssz_snappy")
        )
        roots = _read_yaml(os.path.join(case_dir, "roots.yaml"))
        obj = cls.decode(serialized)
        assert obj.encode() == serialized, "re-serialization mismatch"
        assert obj.hash_tree_root() == _hex(roots["root"])


class Fork(Handler):
    """fork/fork vectors: pre-state (previous fork) + meta {fork} ->
    upgraded post-state (reference: ef_tests fork handler over
    upgrade/{altair,merge}.rs)."""

    runner = "fork"
    handler = "fork"

    _PREV = {"altair": "phase0", "bellatrix": "altair"}

    def run_case(self, case_dir, config, fork):
        from ..consensus.transition.upgrade import (
            upgrade_to_altair,
            upgrade_to_bellatrix,
        )

        meta = _read_yaml(os.path.join(case_dir, "meta.yaml"))
        target = meta["fork"]
        spec = _spec_for(config, target)
        t = spec_types(spec.preset)
        pre_cls = t.STATE_BY_FORK[self._PREV[target]]
        pre = pre_cls.decode(
            _read_ssz_snappy(os.path.join(case_dir, "pre.ssz_snappy"))
        )
        post = (
            upgrade_to_altair(pre, spec)
            if target == "altair"
            else upgrade_to_bellatrix(pre, spec)
        )
        want = _read_ssz_snappy(os.path.join(case_dir, "post.ssz_snappy"))
        assert post.encode() == want, "fork upgrade state mismatch"


class GenesisInitialization(Handler):
    """genesis/initialization: eth1 data + deposits -> genesis state
    (reference: ef_tests genesis handler over genesis.rs)."""

    runner = "genesis"
    handler = "initialization"

    def run_case(self, case_dir, config, fork):
        from ..consensus.genesis import initialize_beacon_state_from_eth1
        from ..consensus.types import Deposit

        spec = _spec_for(config, fork)
        eth1 = _read_yaml(os.path.join(case_dir, "eth1.yaml"))
        meta = _read_yaml(os.path.join(case_dir, "meta.yaml"))
        deposits = [
            Deposit.decode(_read_ssz_snappy(
                os.path.join(case_dir, f"deposits_{i}.ssz_snappy")
            ))
            for i in range(int(meta["deposits_count"]))
        ]
        state = initialize_beacon_state_from_eth1(
            _hex(eth1["eth1_block_hash"]),
            int(eth1["eth1_timestamp"]),
            deposits,
            spec,
        )
        want = _read_ssz_snappy(os.path.join(case_dir, "state.ssz_snappy"))
        assert state.encode() == want, "genesis state mismatch"


class GenesisValidity(Handler):
    runner = "genesis"
    handler = "validity"

    def run_case(self, case_dir, config, fork):
        from ..consensus.genesis import is_valid_genesis_state

        spec = _spec_for(config, fork)
        state = _state_cls(config, fork).decode(
            _read_ssz_snappy(os.path.join(case_dir, "genesis.ssz_snappy"))
        )
        want = bool(_read_yaml(os.path.join(case_dir, "is_valid.yaml")))
        assert is_valid_genesis_state(state, spec) == want


# ------------------------------------------------------------ rewards runner
def _deltas_container():
    from ..consensus.ssz import Container, List as SszList, uint64

    class Deltas(Container):
        fields = {
            "rewards": SszList(uint64, 2**40),
            "penalties": SszList(uint64, 2**40),
        }

    return Deltas


class Rewards(Handler):
    """Per-component reward/penalty deltas vs Deltas ssz files
    (reference: cases/rewards.rs). phase0 checks five components,
    altair+ four (no inclusion_delay)."""

    runner = "rewards"

    def __init__(self, sub: str):
        self.handler = sub

    def run_case(self, case_dir, config, fork):
        from ..consensus.transition.rewards import (
            attestation_deltas_altair,
            attestation_deltas_phase0,
        )

        Deltas = _deltas_container()
        spec = _spec_for(config, fork)
        pre = _state_cls(config, fork).decode(
            _read_ssz_snappy(os.path.join(case_dir, "pre.ssz_snappy"))
        )
        got = (
            attestation_deltas_phase0(pre, spec)
            if fork == "phase0"
            else attestation_deltas_altair(pre, spec)
        )
        for name, (rewards, penalties) in got.items():
            path = os.path.join(case_dir, f"{name}_deltas.ssz_snappy")
            want = Deltas.decode(_read_ssz_snappy(path))
            assert list(want.rewards) == rewards, f"{name} rewards"
            assert list(want.penalties) == penalties, f"{name} penalties"


# --------------------------------------------------------- transition runner
class Transition(Handler):
    """Blocks crossing a fork boundary: pre-fork blocks under the old
    rules, the upgrade at fork_epoch, post-fork blocks under the new
    (reference: cases/transition.rs)."""

    runner = "transition"
    handler = "core"

    _PREV = {"altair": "phase0", "bellatrix": "altair"}

    def run_case(self, case_dir, config, fork):
        import dataclasses

        from ..consensus.transition.block import (
            SignatureStrategy,
            per_block_processing,
        )
        from ..consensus.transition.slot import process_slots

        meta = _read_yaml(os.path.join(case_dir, "meta.yaml"))
        post_fork = meta["post_fork"]
        fork_epoch = int(meta["fork_epoch"])
        count = int(meta["blocks_count"])
        fork_block = meta.get("fork_block")  # index of last pre-fork block

        spec = _spec_for(config, self._PREV[post_fork])
        spec = dataclasses.replace(
            spec,
            ALTAIR_FORK_EPOCH=(
                fork_epoch if post_fork == "altair" else 0
            ),
            BELLATRIX_FORK_EPOCH=(
                fork_epoch if post_fork == "bellatrix" else None
            ),
        )
        t = spec_types(spec.preset)
        pre_fork = self._PREV[post_fork]
        state = t.STATE_BY_FORK[pre_fork].decode(
            _read_ssz_snappy(os.path.join(case_dir, "pre.ssz_snappy"))
        )

        for i in range(count):
            pre_side = fork_block is not None and i <= int(fork_block)
            blk_fork = pre_fork if pre_side else post_fork
            raw = _read_ssz_snappy(
                os.path.join(case_dir, f"blocks_{i}.ssz_snappy")
            )
            block = t.SIGNED_BLOCK_BY_FORK[blk_fork].decode(raw)
            if int(state.slot) < int(block.message.slot):
                # process_slots applies the scheduled fork upgrade at the
                # boundary (transition/slot.py _maybe_upgrade)
                state = process_slots(state, int(block.message.slot), spec)
            per_block_processing(
                state, block, spec, strategy=SignatureStrategy.VERIFY_BULK
            )
        want = _read_ssz_snappy(os.path.join(case_dir, "post.ssz_snappy"))
        assert state.encode() == want, "transition post-state mismatch"


# -------------------------------------------------------- fork_choice runner
class ForkChoiceHandler(Handler):
    """Step-driven fork-choice vectors: anchor + {tick, block,
    attestation, checks} steps (reference: cases/fork_choice.rs)."""

    runner = "fork_choice"

    def __init__(self, sub: str):
        self.handler = sub

    def run_case(self, case_dir, config, fork):
        from ..consensus import helpers as ch
        from ..consensus.transition.block import (
            SignatureStrategy,
            per_block_processing,
        )
        from ..consensus.transition.slot import process_slots
        from ..forkchoice.fork_choice import ForkChoice, ForkChoiceError

        spec = _spec_for(config, fork)
        t = spec_types(spec.preset)
        state_cls = _state_cls(config, fork)
        anchor_state = state_cls.decode(
            _read_ssz_snappy(os.path.join(case_dir, "anchor_state.ssz_snappy"))
        )
        anchor_block = t.BLOCK_BY_FORK[fork].decode(
            _read_ssz_snappy(os.path.join(case_dir, "anchor_block.ssz_snappy"))
        )
        anchor_root = anchor_block.hash_tree_root()
        fc = ForkChoice.from_anchor(anchor_state, anchor_root, spec)
        states = {anchor_root: anchor_state}
        genesis_time = int(anchor_state.genesis_time)
        current_slot = int(anchor_state.slot)

        steps = _read_yaml(os.path.join(case_dir, "steps.yaml"))
        for step in steps:
            if "tick" in step:
                current_slot = (
                    int(step["tick"]) - genesis_time
                ) // spec.SECONDS_PER_SLOT
                fc.update_time(current_slot)
            elif "block" in step:
                raw = _read_ssz_snappy(
                    os.path.join(case_dir, f"{step['block']}.ssz_snappy")
                )
                signed = t.SIGNED_BLOCK_BY_FORK[fork].decode(raw)
                expect_valid = step.get("valid", True)
                try:
                    parent = states[bytes(signed.message.parent_root)].copy()
                    if int(parent.slot) < int(signed.message.slot):
                        parent = process_slots(
                            parent, int(signed.message.slot), spec
                        )
                    per_block_processing(
                        parent, signed, spec,
                        strategy=SignatureStrategy.VERIFY_BULK,
                    )
                    root = signed.message.hash_tree_root()
                    fc.on_block(current_slot, signed.message, root, parent)
                except Exception:
                    if expect_valid:
                        raise
                    continue
                assert expect_valid, "expected on_block rejection"
                states[root] = parent
            elif "attestation" in step:
                raw = _read_ssz_snappy(
                    os.path.join(case_dir, f"{step['attestation']}.ssz_snappy")
                )
                att = t.Attestation.decode(raw)
                st = states.get(bytes(att.data.beacon_block_root))
                indexed = ch.get_indexed_attestation(st, att, spec)
                expect_valid = step.get("valid", True)
                try:
                    fc.on_attestation(current_slot, indexed)
                except ForkChoiceError:
                    if expect_valid:
                        raise
                    continue
                assert expect_valid, "expected on_attestation rejection"
            elif "checks" in step:
                checks = step["checks"]
                if "head" in checks:
                    head = fc.get_head(current_slot)
                    assert head == _hex(checks["head"]["root"]), "head root"
                    hb = fc.get_block(head)
                    assert hb.slot == int(checks["head"]["slot"]), "head slot"
                if "justified_checkpoint" in checks:
                    cp = checks["justified_checkpoint"]
                    assert fc.store.justified_checkpoint == (
                        int(cp["epoch"]), _hex(cp["root"])
                    ), "justified checkpoint"
                if "finalized_checkpoint" in checks:
                    cp = checks["finalized_checkpoint"]
                    assert fc.store.finalized_checkpoint == (
                        int(cp["epoch"]), _hex(cp["root"])
                    ), "finalized checkpoint"


# --------------------------------------------------------- ssz_generic runner
def _ssz_generic_schema(handler: str, case_name: str):
    """Schema from the official case-name conventions
    (reference: cases/ssz_generic.rs type_name parsing)."""
    from ..consensus.ssz import (
        Bitlist,
        Bitvector,
        Boolean,
        Uint,
        Vector,
    )

    parts = case_name.split("_")
    if handler == "uints":
        # uint_{bits}_{...}
        return Uint(int(parts[1]) // 8)
    if handler == "boolean":
        return Boolean()
    if handler == "bitvector":
        # bitvec_{n}_{...}
        return Bitvector(int(parts[1]))
    if handler == "bitlist":
        # bitlist_{n}_{...}
        return Bitlist(int(parts[1]))
    if handler == "basic_vector":
        # vec_{elem}_{n}_{...}
        elem = {
            "bool": Boolean(),
            "uint8": Uint(1), "uint16": Uint(2), "uint32": Uint(4),
            "uint64": Uint(8), "uint128": Uint(16), "uint256": Uint(32),
        }[parts[1]]
        return Vector(elem, int(parts[2]))
    if handler == "containers":
        return _ssz_test_container(parts[0]).schema
    raise KeyError(handler)


_SSZ_TEST_CONTAINERS: dict = {}


def _ssz_test_container(name: str):
    """The spec's ssz_generic test containers (SingleFieldTestStruct &
    co., reference: cases/ssz_generic.rs:20-80)."""
    if _SSZ_TEST_CONTAINERS:
        return _SSZ_TEST_CONTAINERS[name]
    from ..consensus.ssz import (
        Bitlist,
        Bitvector,
        Container,
        List as SszList,
        Uint,
        Vector,
    )

    u8, u16, u32, u64 = Uint(1), Uint(2), Uint(4), Uint(8)

    class SingleFieldTestStruct(Container):
        fields = {"A": u8}

    class SmallTestStruct(Container):
        fields = {"A": u16, "B": u16}

    class FixedTestStruct(Container):
        fields = {"A": u8, "B": u64, "C": u32}

    class VarTestStruct(Container):
        fields = {"A": u16, "B": SszList(u16, 1024), "C": u8}

    class ComplexTestStruct(Container):
        fields = {
            "A": u16,
            "B": SszList(u16, 128),
            "C": u8,
            "D": SszList(u8, 256),
            "E": VarTestStruct.schema,
            "F": Vector(FixedTestStruct.schema, 4),
            "G": Vector(VarTestStruct.schema, 2),
        }

    class BitsStruct(Container):
        fields = {
            "A": Bitlist(5),
            "B": Bitvector(2),
            "C": Bitvector(1),
            "D": Bitlist(6),
            "E": Bitvector(8),
        }

    _SSZ_TEST_CONTAINERS.update({
        "SingleFieldTestStruct": SingleFieldTestStruct,
        "SmallTestStruct": SmallTestStruct,
        "FixedTestStruct": FixedTestStruct,
        "VarTestStruct": VarTestStruct,
        "ComplexTestStruct": ComplexTestStruct,
        "BitsStruct": BitsStruct,
    })
    return _SSZ_TEST_CONTAINERS[name]


class SszGeneric(Handler):
    """valid/ cases must round-trip and match the recorded root; invalid/
    cases must fail to decode (reference: cases/ssz_generic.rs)."""

    runner = "ssz_generic"

    def __init__(self, sub: str):
        self.handler = sub

    def run_case(self, case_dir, config, fork):
        from ..consensus.ssz import SszError

        suite = os.path.basename(os.path.dirname(case_dir))
        name = os.path.basename(case_dir)
        schema = _ssz_generic_schema(self.handler, name)
        raw = _read_ssz_snappy(os.path.join(case_dir, "serialized.ssz_snappy"))
        if suite == "invalid":
            try:
                schema.decode(raw)
            except (SszError, ValueError, IndexError):
                return
            raise AssertionError("invalid case decoded successfully")
        obj = schema.decode(raw)
        enc = obj.encode() if hasattr(obj, "encode") else schema.encode(obj)
        assert enc == raw, "re-serialization mismatch"
        meta = _read_yaml(os.path.join(case_dir, "meta.yaml"))
        root = (
            obj.hash_tree_root()
            if hasattr(obj, "hash_tree_root")
            else schema.hash_tree_root(obj)
        )
        assert root == _hex(meta["root"])


# -------------------------------------------------------------------- driver
def default_handlers() -> list[Handler]:
    hs: list[Handler] = [
        BlsSign(), BlsVerify(), BlsAggregate(), BlsAggregateVerify(),
        BlsFastAggregateVerify(), BlsEthAggregatePubkeys(),
        BlsEthFastAggregateVerify(),
        Shuffling(),
        SanitySlots(), SanityBlocks(),
    ]
    hs += [Operations(op) for op in _OP_FILES]
    hs += [EpochProcessing(s) for s in EpochProcessing.SUBS]
    hs += [
        SszStatic(n)
        for n in (
            "Attestation", "AttestationData", "AttesterSlashing",
            "BeaconBlockHeader", "Checkpoint", "Deposit", "DepositData",
            "DepositMessage", "Eth1Data", "Fork", "ForkData",
            "HistoricalBatch", "IndexedAttestation", "PendingAttestation",
            "ProposerSlashing", "SignedBeaconBlockHeader",
            "SignedVoluntaryExit", "SigningData", "SyncAggregate",
            "SyncCommittee", "Validator", "VoluntaryExit",
            "ExecutionPayload", "ExecutionPayloadHeader",
        )
    ]
    hs += [Fork(), GenesisInitialization(), GenesisValidity()]
    hs += [Rewards("basic"), Transition(), ForkChoiceHandler("get_head"),
           ForkChoiceHandler("on_block")]
    hs += [
        SszGeneric(s)
        for s in ("uints", "boolean", "basic_vector", "bitvector",
                  "bitlist", "containers")
    ]
    return hs


def run_handler(root: str, handler: Handler,
                configs=("general", "minimal", "minimal_exitable", "minimal_smallgenesis", "mainnet")) -> list[CaseResult]:
    """Walk tests/<config>/<fork>/<runner>/<handler>/<suite>/<case>."""
    results: list[CaseResult] = []
    tests_root = os.path.join(root, "tests")
    for config in configs:
        config_dir = os.path.join(tests_root, config)
        if not os.path.isdir(config_dir):
            continue
        for fork in sorted(os.listdir(config_dir)):
            hdir = os.path.join(config_dir, fork, handler.runner, handler.handler)
            if not os.path.isdir(hdir):
                continue
            for suite in sorted(os.listdir(hdir)):
                sdir = os.path.join(hdir, suite)
                for case in sorted(os.listdir(sdir)):
                    case_dir = os.path.join(sdir, case)
                    if not os.path.isdir(case_dir):
                        continue
                    try:
                        handler.run_case(case_dir, config, fork)
                        results.append(
                            CaseResult(handler.handler, case_dir, True)
                        )
                    except Exception as e:
                        results.append(
                            CaseResult(handler.handler, case_dir, False, repr(e))
                        )
    return results


def run_all(root: str, handlers: list[Handler] | None = None) -> dict:
    """Run every handler; enforce that present runners were exercised
    (the check_all_files_accessed.py coverage rule)."""
    handlers = handlers if handlers is not None else default_handlers()
    all_results: list[CaseResult] = []
    by_handler: dict[str, int] = {}
    for handler in handlers:
        results = run_handler(root, handler)
        all_results.extend(results)
        by_handler[f"{handler.runner}/{handler.handler}"] = len(results)
    failures = [r for r in all_results if not r.passed]
    return {
        "total": len(all_results),
        "failures": failures,
        "by_handler": by_handler,
    }
