"""Registry-scale slot drive: config #5 THROUGH THE CHAIN.

The bench's slot mode (bench.py slot_mode) measures the BLS layer on a
fixture; this module runs the same scale through the real node stack —
a BeaconChain at N validators (device-built blsrt registry, lazy pubkey
cache), gossip-shaped SignedAggregateAndProof objects entering via the
BeaconProcessor's aggregate queue, the Router's batch handler verifying
all of a slot's aggregates in ONE device batch (3 signature sets per
aggregate), and fork choice observing every attester — head update out
(VERDICT r3 item 9; reference: beacon_processor/mod.rs:1004-1070 worker
pools + attestation_verification/batch.rs).

Key scale techniques:
  * sequential-key registry (sk_i = i+1): pubkeys from one device table
    build; a committee's aggregate signature is (sum sk_i mod r)*H(m);
  * aggregate/selection/aggregator signatures via ``bulk_g2_mul`` — one
    hash per distinct message, scalar multiplications batched on the
    device G2 kernel (host fallback off-TPU);
  * the aggregator search evaluates candidates' selection proofs until
    one passes is_aggregator, exactly the VC's duty check.
"""

from __future__ import annotations

import time

import numpy as np

from ..common.slot_clock import ManualSlotClock
from ..consensus import helpers as h
from ..consensus.config import ChainSpec, compute_signing_root
from ..consensus.genesis import scale_genesis_state
from ..consensus.ssz import uint64
from ..consensus.types import spec_types
from ..crypto.bls.api import AggregateSignature, Signature
from ..crypto.bls.constants import R as CURVE_ORDER
from ..crypto.bls.curve import g2_to_compressed
from ..crypto.bls.hash_to_curve import hash_to_g2
from ..store.hot_cold import HotColdDB, StoreConfig
from ..store.kv import MemoryStore
from .beacon_chain import BeaconChain
from .pubkey_cache import ValidatorPubkeyCache


def slot_shape(n_validators: int, spec: ChainSpec) -> tuple[int, int]:
    """(committees_per_slot, committee_size) for a registry of
    ``n_validators`` active validators — the spec's
    get_committee_count_per_slot formula without needing a state.
    loadgen/traffic.py seeds its per-slot committee structure from
    this; at mainnet 1M validators: 64 committees of ~488."""
    p = spec.preset
    committees = max(
        1,
        min(
            p.MAX_COMMITTEES_PER_SLOT,
            n_validators // p.SLOTS_PER_EPOCH // p.TARGET_COMMITTEE_SIZE,
        ),
    )
    size = max(1, n_validators // (p.SLOTS_PER_EPOCH * committees))
    return committees, size


def bulk_g2_mul(point, scalars: list[int]):
    """[k]P for one G2 point and many scalars.

    On TPU: one fused scalar-mul kernel call over all lanes
    (ops/tkernel_calls.scalar_mul_g2_t). Off-TPU the kernel would run
    in minutes-slow interpret mode, so small batches fall back to host
    muls — identical results, oracle-tested."""
    import jax

    if jax.default_backend() != "tpu" or len(scalars) < 8:
        return [point.mul(s) for s in scalars]

    import jax.numpy as jnp

    from ..ops import points as pts
    from ..ops.tkernel_calls import scalar_mul_g2_t, to_affine_g2_t
    from ..ops import tkernel as tk

    n = len(scalars)
    px, py, _ = pts.g2_to_dev([point])
    # transposed layout: [2, 48] coefficient planes broadcast over lanes
    x = jnp.broadcast_to(jnp.asarray(px[0])[:, :, None], (2, 48, n))
    y = jnp.broadcast_to(jnp.asarray(py[0])[:, :, None], (2, 48, n))
    inf = jnp.zeros((1, n), jnp.int32)
    bits = np.zeros((256, n), np.int32)
    for j, s in enumerate(scalars):
        for b in range(256):
            bits[b, j] = (s >> (255 - b)) & 1
    acc = scalar_mul_g2_t(x, y, inf, jnp.asarray(bits))
    ax, ay, ainf = to_affine_g2_t(acc)
    return pts.g2_from_dev(
        np.moveaxis(np.asarray(ax), -1, 0),
        np.moveaxis(np.asarray(ay), -1, 0),
        np.asarray(ainf)[0] != 0,
    )


class ScaleChain:
    """A chain at registry scale plus the processor/router plumbing."""

    def __init__(self, n_validators: int, spec: ChainSpec,
                 genesis_time: int = 1_600_000_000):
        from .. import blsrt

        t0 = time.perf_counter()
        self.table = blsrt.build_sequential_table(n_validators)
        self.table_build_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        self.compressed = blsrt.compressed_pubkeys(self.table)
        self.compress_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        state = scale_genesis_state(self.compressed, genesis_time, spec)
        self.state_build_s = time.perf_counter() - t0

        self.spec = spec
        self.types = spec_types(spec.preset)
        self.slot_clock = ManualSlotClock(
            genesis_time, spec.SECONDS_PER_SLOT
        )
        cache = ValidatorPubkeyCache.from_device_table(
            self.table, self.compressed
        )
        blsrt.set_device_table(self.table)

        t0 = time.perf_counter()
        hot_cold = HotColdDB(
            MemoryStore(), spec,
            StoreConfig(slots_per_restore_point=spec.preset.SLOTS_PER_EPOCH),
        )
        self.chain = BeaconChain.from_genesis(
            hot_cold, state, spec, self.slot_clock,
            backend="jax", pubkey_cache=cache,
        )
        self.chain_init_s = time.perf_counter() - t0

        from ..network.processor import BeaconProcessor
        from ..network.router import Router

        self.processor = BeaconProcessor(attestation_batch_size=4096)
        self.router = Router(
            self.chain, self.processor, peer_manager=_NullPeerManager(),
            publish=None,
        )

    # ------------------------------------------------------- slot load
    def make_slot_aggregates(self, slot: int):
        """Gossip-shaped SignedAggregateAndProof for EVERY committee of
        ``slot``: full participation, real signatures from the
        sequential-key registry."""
        t = self.types
        spec = self.spec
        state = self.chain.head().state
        epoch = slot // spec.preset.SLOTS_PER_EPOCH
        n_comm = h.get_committee_count_per_slot(state, epoch, spec)

        att_domain = spec.get_domain(
            spec.DOMAIN_BEACON_ATTESTER, epoch, state.fork,
            state.genesis_validators_root,
        )
        sel_domain = spec.get_domain(
            spec.DOMAIN_SELECTION_PROOF, epoch, state.fork,
            state.genesis_validators_root,
        )
        agg_domain = spec.get_domain(
            spec.DOMAIN_AGGREGATE_AND_PROOF, epoch, state.fork,
            state.genesis_validators_root,
        )
        from ..consensus.signature_sets import signing_root_of_root

        slot_root = signing_root_of_root(
            uint64.hash_tree_root(slot), sel_domain
        )
        h_slot = hash_to_g2(slot_root)

        out = []
        for ci in range(n_comm):
            att = self.chain.produce_unaggregated_attestation(slot, ci)
            committee = h.get_beacon_committee(state, slot, ci, spec)
            data = att.data
            att_root = compute_signing_root(data, att_domain)
            sk_sum = sum(int(i) + 1 for i in committee) % CURVE_ORDER
            agg_sig = AggregateSignature(hash_to_g2(att_root).mul(sk_sum))

            full = t.Attestation(
                aggregation_bits=[True] * len(committee), data=data,
                signature=g2_to_compressed(agg_sig.point),
            )

            # aggregator search: first member whose selection proof
            # passes is_aggregator (the VC duty check). Chunked over the
            # WHOLE committee: at mainnet-1M committee sizes (~488) the
            # modulo is ~30, so a fixed 64-candidate cap fails some
            # committee almost every slot.
            agg_index = None
            proof = None
            members = [int(i) for i in committee]
            for lo in range(0, len(members), 64):
                cand = members[lo:lo + 64]
                proofs = bulk_g2_mul(
                    h_slot, [(i + 1) % CURVE_ORDER for i in cand]
                )
                for vi, pt in zip(cand, proofs):
                    pb = g2_to_compressed(pt)
                    if h.is_aggregator(len(committee), pb, spec):
                        agg_index, proof = vi, pb
                        break
                if agg_index is not None:
                    break
            if agg_index is None:
                # P ~ (1-1/modulo)^len: ~3e-8 at len 488; committees
                # without an elected aggregator simply have no aggregate
                # that slot (the spec allows this) — skip it.
                continue

            msg = t.AggregateAndProof(
                aggregator_index=agg_index, aggregate=full,
                selection_proof=proof,
            )
            outer_root = compute_signing_root(msg, agg_domain)
            outer = hash_to_g2(outer_root).mul((agg_index + 1) % CURVE_ORDER)
            out.append(t.SignedAggregateAndProof(
                message=msg, signature=g2_to_compressed(outer)
            ))
        return out

    def drive_slot(self, aggregates) -> dict:
        """Feed one slot's aggregates through the processor queues and
        drain — the gossip worker path — then report head/fork-choice
        effects and timing."""
        from ..network.processor import WorkEvent, WorkType

        t0 = time.perf_counter()
        for sa in aggregates:
            self.processor.send(WorkEvent(
                work_type=WorkType.GOSSIP_AGGREGATE, payload=sa,
                peer_id=None,
            ))
        self.processor.process_pending()
        wall = time.perf_counter() - t0
        return {
            "slot_wall_s": wall,
            "aggregates_verified": self.router.stats["aggregates_verified"],
            "attestations_rejected": self.router.stats["attestations_rejected"],
        }


class _NullPeerManager:
    def report_peer(self, peer_id, action):
        pass

    def is_connected(self, peer_id):
        return False

