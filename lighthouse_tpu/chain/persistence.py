"""Chain persistence — restart from disk (reference:
beacon_chain/src/persisted_{beacon_chain,fork_choice}.rs +
operation_pool/src/persistence.rs + fork_revert.rs).

Everything the node needs to resume lives in the store:

* ``PersistedForkChoice``  — proto-array nodes, vote trackers,
  checkpoints (the reference's SSZ container, here a compact
  hex-JSON encoding in the metadata column);
* ``PersistedBeaconChain`` — head root + genesis root;
* op-pool contents       — attestations and SigVerifiedOps re-encoded
  as their SSZ containers.

``save_chain`` writes all three; ``load_chain`` rebuilds a BeaconChain
(falling back to ``reset_fork_choice_to_finalization`` — fork_revert.rs
— when the persisted fork choice is missing or corrupt: replay hot
blocks from the finalized snapshot).
"""

from __future__ import annotations

import json

from ..forkchoice import ExecutionStatus, ForkChoice
from ..forkchoice.fork_choice import ForkChoiceStore
from ..forkchoice.proto_array import VoteTracker

KEY_PERSISTED_CHAIN = b"persisted_beacon_chain"
KEY_PERSISTED_FORK_CHOICE = b"persisted_fork_choice"
KEY_PERSISTED_OP_POOL = b"persisted_op_pool"


def _hx(b: bytes | None) -> str | None:
    return None if b is None else b.hex()


def _unhx(s: str | None) -> bytes | None:
    return None if s is None else bytes.fromhex(s)


def _cp(t) -> list:
    return [int(t[0]), t[1].hex()]


def _uncp(v) -> tuple:
    return (int(v[0]), bytes.fromhex(v[1]))


# ------------------------------------------------------------- fork choice
def serialize_fork_choice(fc: ForkChoice) -> bytes:
    proto = fc.proto
    nodes = []
    for n in proto.proto_array.nodes:
        nodes.append(
            {
                "slot": n.slot,
                "root": _hx(n.root),
                "state_root": _hx(n.state_root),
                "target_root": _hx(n.target_root),
                "parent": n.parent,
                "jc": _cp(n.justified_checkpoint),
                "fc": _cp(n.finalized_checkpoint),
                "weight": n.weight,
                "best_child": n.best_child,
                "best_descendant": n.best_descendant,
                "exec": n.execution_status.value,
                "exec_hash": _hx(n.execution_block_hash),
            }
        )
    votes = [
        {"c": _hx(v.current_root), "n": _hx(v.next_root), "e": v.next_epoch}
        for v in proto.votes
    ]
    store = fc.store
    doc = {
        "nodes": nodes,
        "votes": votes,
        "balances": list(proto.balances),
        "justified": _cp(store.justified_checkpoint),
        "finalized": _cp(store.finalized_checkpoint),
        "best_justified": _cp(store.best_justified_checkpoint),
        "equivocating": sorted(store.equivocating_indices),
        "current_slot": fc._current_slot,
        "genesis_time": fc.genesis_time,
    }
    return json.dumps(doc).encode()


def deserialize_fork_choice(raw: bytes, spec, balances_fn) -> ForkChoice:
    doc = json.loads(raw)
    justified = _uncp(doc["justified"])
    finalized = _uncp(doc["finalized"])

    # rebuild through the anchor path then restore node/vote state
    nodes = doc["nodes"]
    if not nodes:
        raise ValueError("persisted fork choice has no nodes")
    from ..forkchoice.proto_array import ProtoArray, ProtoArrayForkChoice, _Node

    proto = ProtoArrayForkChoice.__new__(ProtoArrayForkChoice)

    proto.proto_array = ProtoArray(justified, finalized)
    proto.votes = [
        VoteTracker(
            current_root=_unhx(v["c"]), next_root=_unhx(v["n"]),
            next_epoch=int(v["e"]),
        )
        for v in doc["votes"]
    ]
    proto.balances = [int(b) for b in doc["balances"]]
    for n in nodes:
        node = _Node(
            slot=int(n["slot"]),
            root=_unhx(n["root"]),
            state_root=_unhx(n["state_root"]),
            target_root=_unhx(n["target_root"]),
            parent=n["parent"],
            justified_checkpoint=_uncp(n["jc"]),
            finalized_checkpoint=_uncp(n["fc"]),
            weight=int(n["weight"]),
            best_child=n["best_child"],
            best_descendant=n["best_descendant"],
            execution_status=ExecutionStatus(n["exec"]),
            execution_block_hash=_unhx(n["exec_hash"]),
        )
        proto.proto_array.indices[node.root] = len(proto.proto_array.nodes)
        proto.proto_array.nodes.append(node)

    store = ForkChoiceStore(
        justified_checkpoint=justified,
        finalized_checkpoint=finalized,
        best_justified_checkpoint=_uncp(doc["best_justified"]),
        justified_balances=[],
        balances_fn=balances_fn,
    )
    store.equivocating_indices = set(doc["equivocating"])
    store.refresh_justified_balances()
    fc = ForkChoice(store, proto, spec, int(doc["genesis_time"]))
    fc._current_slot = int(doc["current_slot"])
    return fc


# ----------------------------------------------------------------- op pool
def serialize_op_pool(pool) -> bytes:
    doc = {
        "attestations": [
            a.encode().hex() for a in pool.all_attestations()
        ],
        "proposer_slashings": [
            {"op": op.operation.encode().hex(),
             "vv": [[e, v.hex()] for e, v in op.verified_versions]}
            for op in pool.proposer_slashings.values()
        ],
        "attester_slashings": [
            {"op": op.operation.encode().hex(),
             "vv": [[e, v.hex()] for e, v in op.verified_versions]}
            for op in pool.attester_slashings
        ],
        "voluntary_exits": [
            {"op": op.operation.encode().hex(),
             "vv": [[e, v.hex()] for e, v in op.verified_versions]}
            for op in pool.voluntary_exits.values()
        ],
    }
    return json.dumps(doc).encode()


def deserialize_into_op_pool(raw: bytes, pool, types) -> None:
    from ..consensus.types import ProposerSlashing, SignedVoluntaryExit
    from ..consensus.verify_operation import SigVerifiedOp

    doc = json.loads(raw)

    def unop(entry, cls):
        return SigVerifiedOp(
            cls.decode(bytes.fromhex(entry["op"])),
            [(int(e), bytes.fromhex(v)) for e, v in entry["vv"]],
        )

    for hexed in doc["attestations"]:
        pool.insert_attestation(types.Attestation.decode(bytes.fromhex(hexed)))
    for entry in doc["proposer_slashings"]:
        pool.insert_proposer_slashing(unop(entry, ProposerSlashing))
    for entry in doc["attester_slashings"]:
        pool.insert_attester_slashing(unop(entry, types.AttesterSlashing))
    for entry in doc["voluntary_exits"]:
        pool.insert_voluntary_exit(unop(entry, SignedVoluntaryExit))


# ------------------------------------------------------------------- chain
def save_chain(chain) -> None:
    """Persist head pointer, fork choice, and op pool
    (beacon_chain.rs persist_head + persist_fork_choice + persist_op_pool)."""
    store = chain.store
    store.put_meta(
        KEY_PERSISTED_CHAIN,
        json.dumps(
            {
                "head_root": chain.head().root.hex(),
                "genesis_block_root": chain.genesis_block_root.hex(),
                "finalized": _cp(chain.finalized_checkpoint()),
                # the backend is part of chain identity: a fake-crypto
                # chain must never resume under real verification
                "backend": chain.backend,
            }
        ).encode(),
    )
    store.put_meta(KEY_PERSISTED_FORK_CHOICE, serialize_fork_choice(chain.fork_choice))
    store.put_meta(KEY_PERSISTED_OP_POOL, serialize_op_pool(chain.op_pool))


def load_chain(store, spec, slot_clock, backend=None):
    """Rebuild a BeaconChain from a persisted store (the FromStore boot
    path, builder.rs ClientGenesis::FromStore). ``backend=None`` resumes
    with the backend the chain was persisted under."""
    from .beacon_chain import BeaconChain

    raw = store.get_meta(KEY_PERSISTED_CHAIN)
    if raw is None:
        raise ValueError("store holds no persisted chain")
    doc = json.loads(raw)
    head_root = bytes.fromhex(doc["head_root"])
    genesis_block_root = bytes.fromhex(doc["genesis_block_root"])
    if backend is None:
        backend = doc.get("backend")

    head_block = store.get_block(head_root)
    if head_block is None:
        raise ValueError("persisted head block missing")
    head_state = store.get_state(bytes(head_block.message.state_root))
    if head_state is None:
        raise ValueError("persisted head state missing")

    chain = BeaconChain.__new__(BeaconChain)
    BeaconChain.__init__(
        chain, spec, store, slot_clock, head_state, head_block,
        genesis_block_root, backend,
    )
    # __init__ anchored fork choice at the head; replace with the
    # persisted one (or rebuild from finalization if absent/corrupt)
    raw_fc = store.get_meta(KEY_PERSISTED_FORK_CHOICE)
    if raw_fc is not None:
        try:
            chain.fork_choice = deserialize_fork_choice(
                raw_fc, spec, chain._justified_balances
            )
        except (ValueError, KeyError):
            reset_fork_choice_to_finalization(chain)
    else:
        reset_fork_choice_to_finalization(chain)
    chain.genesis_block_root = genesis_block_root
    chain._finalized_checkpoint = _uncp(doc["finalized"])
    from .beacon_chain import HeadInfo

    chain._head = HeadInfo(head_root, head_block, head_state)
    chain.snapshot_cache.insert(head_root, head_state.copy())

    raw_pool = store.get_meta(KEY_PERSISTED_OP_POOL)
    if raw_pool is not None:
        try:
            deserialize_into_op_pool(raw_pool, chain.op_pool, chain.types)
        except (ValueError, KeyError):
            pass  # op pool is best-effort state
    return chain


def reset_fork_choice_to_finalization(chain) -> None:
    """fork_revert.rs reset_fork_choice_to_finalization: rebuild fork
    choice anchored at the FINALIZED block and replay every descendant
    block in the hot store on top (all branches, not just the head)."""
    store = chain.store
    fin_epoch, fin_root = chain.finalized_checkpoint()
    anchor_root = fin_root
    anchor_block = store.get_block(anchor_root)
    anchor_state = None
    if anchor_block is not None:
        anchor_state = store.get_state(bytes(anchor_block.message.state_root))
    if anchor_state is None:
        # finalized snapshot unavailable (pruned): fall back to the head
        head = chain.head()
        anchor_root, anchor_state = head.root, head.state
    chain.fork_choice = ForkChoice.from_anchor(
        anchor_state, anchor_root, chain.spec,
        balances_fn=chain._justified_balances,
    )
    # replay hot blocks above the anchor, parents before children
    from ..store.hot_cold import COL_BLOCK

    anchor_slot = int(anchor_state.slot)
    blocks = []
    for key, raw in store.db.iter_column(COL_BLOCK):
        block = store._decode_block(raw)
        if int(block.message.slot) > anchor_slot:
            blocks.append((int(block.message.slot), key, block))
    for slot, root, block in sorted(blocks, key=lambda x: x[0]):
        state = store.get_state(bytes(block.message.state_root))
        if state is None or not chain.fork_choice.contains_block(
            bytes(block.message.parent_root)
        ):
            continue
        try:
            chain.fork_choice.on_block(
                max(anchor_slot, slot), block.message, root, state,
                execution_status=ExecutionStatus.IRRELEVANT,
            )
        except Exception:  # lhtpu: ignore[LH502] -- replay tolerates stored blocks orphaned by a pruned fork
            continue
