"""Chain-level caches and anti-equivocation observation sets.

Capability mirrors (reference paths in beacon_node/beacon_chain/src/):

* ShufflingCache (shuffling_cache.rs) — CommitteeCaches keyed by
  (target_epoch, shuffling_decision_root).
* SnapshotCache (snapshot_cache.rs) — recent post-states by block root, so
  block import starts from a warm pre-state.
* BeaconProposerCache (beacon_proposer_cache.rs) — proposer indices per
  (epoch, decision_root).
* ObservedAttesters / ObservedAggregates / ObservedBlockProducers /
  ObservedOperations (observed_*.rs) — dedup/equivocation guards for
  gossip.
* NaiveAggregationPool (naive_aggregation_pool.rs) — aggregates
  unaggregated gossip attestations per data root until aggregators pick
  them up.
"""

from __future__ import annotations

from collections import OrderedDict, defaultdict

from ..consensus.committee_cache import CommitteeCache
from ..crypto.bls.api import AggregateSignature


class ShufflingCache:
    """(epoch, decision_root) -> CommitteeCache, bounded LRU."""

    def __init__(self, capacity: int = 16):
        self.capacity = capacity
        self._map: OrderedDict[tuple, CommitteeCache] = OrderedDict()

    def get(self, epoch: int, decision_root: bytes) -> CommitteeCache | None:
        key = (epoch, bytes(decision_root))
        cache = self._map.get(key)
        if cache is not None:
            self._map.move_to_end(key)
        return cache

    def get_or_init(self, state, epoch: int, decision_root: bytes, spec):
        cache = self.get(epoch, decision_root)
        if cache is None:
            cache = CommitteeCache.initialized(state, epoch, spec)
            self.insert(epoch, decision_root, cache)
        return cache

    def insert(self, epoch: int, decision_root: bytes, cache: CommitteeCache):
        key = (epoch, bytes(decision_root))
        self._map[key] = cache
        self._map.move_to_end(key)
        while len(self._map) > self.capacity:
            self._map.popitem(last=False)


class SnapshotCache:
    """block_root -> (pre_state for children of that block). Bounded."""

    def __init__(self, capacity: int = 4):
        self.capacity = capacity
        self._map: OrderedDict[bytes, object] = OrderedDict()

    def insert(self, block_root: bytes, state) -> None:
        self._map[bytes(block_root)] = state
        self._map.move_to_end(bytes(block_root))
        while len(self._map) > self.capacity:
            self._map.popitem(last=False)

    def get_cloned(self, block_root: bytes):
        state = self._map.get(bytes(block_root))
        return state.copy() if state is not None else None

    def get_state_for_block_processing(self, block_root: bytes):
        """Remove-and-return (the caller consumes the snapshot)."""
        return self._map.pop(bytes(block_root), None)


class BeaconProposerCache:
    """(epoch, decision_root) -> [proposer index per slot in epoch]."""

    def __init__(self, capacity: int = 16):
        self.capacity = capacity
        self._map: OrderedDict[tuple, list[int]] = OrderedDict()

    def get_slot(self, epoch: int, decision_root: bytes, slot: int, slots_per_epoch: int) -> int | None:
        entry = self._map.get((epoch, bytes(decision_root)))
        if entry is None:
            return None
        return entry[slot % slots_per_epoch]

    def insert(self, epoch: int, decision_root: bytes, proposers: list[int]):
        self._map[(epoch, bytes(decision_root))] = list(proposers)
        while len(self._map) > self.capacity:
            self._map.popitem(last=False)


class ObservedAttesters:
    """(validator, target_epoch) dedup for unaggregated attestations
    (reference: observed_attesters.rs). Finalized epochs are pruned."""

    def __init__(self):
        self._seen: dict[int, set[int]] = defaultdict(set)  # epoch -> validators

    def observe(self, epoch: int, validator_index: int) -> bool:
        """Returns True if ALREADY seen (i.e. duplicate)."""
        seen = validator_index in self._seen[epoch]
        self._seen[epoch].add(validator_index)
        return seen

    def is_known(self, epoch: int, validator_index: int) -> bool:
        return validator_index in self._seen.get(epoch, ())

    def prune(self, finalized_epoch: int) -> None:
        for e in [e for e in self._seen if e < finalized_epoch]:
            del self._seen[e]


class ObservedAggregates:
    """Attestation-root dedup for aggregates, and (aggregator, epoch)
    tracking (reference: observed_aggregates.rs)."""

    def __init__(self):
        self._roots: dict[int, set[bytes]] = defaultdict(set)  # epoch -> att roots
        self._aggregators: dict[int, set[int]] = defaultdict(set)

    def observe_root(self, epoch: int, att_root: bytes) -> bool:
        seen = att_root in self._roots[epoch]
        self._roots[epoch].add(att_root)
        return seen

    def observe_aggregator(self, epoch: int, aggregator_index: int) -> bool:
        seen = aggregator_index in self._aggregators[epoch]
        self._aggregators[epoch].add(aggregator_index)
        return seen

    # Check-only queries: batch verification dedups AFTER signature
    # checks (an invalid copy must not censor the valid aggregate), so
    # pre-checks may only LOOK, never record.
    def is_known_root(self, epoch: int, att_root: bytes) -> bool:
        return att_root in self._roots.get(epoch, ())

    def is_known_aggregator(self, epoch: int, aggregator_index: int) -> bool:
        return aggregator_index in self._aggregators.get(epoch, ())

    def prune(self, finalized_epoch: int) -> None:
        for m in (self._roots, self._aggregators):
            for e in [e for e in m if e < finalized_epoch]:
                del m[e]


class ObservedBlockProducers:
    """(proposer, slot) equivocation guard (observed_block_producers.rs).

    Gossip verification only *checks* (``is_known``); the pipeline
    records (``observe``) after the block fully verifies, so junk
    blocks cannot poison a (slot, proposer) pair the honest proposer
    still needs (reference: observe_proposer placement after the
    proposal-signature check in block_verification.rs)."""

    def __init__(self):
        self._seen: dict[int, set[int]] = defaultdict(set)  # slot -> proposers

    def observe(self, slot: int, proposer_index: int) -> bool:
        seen = proposer_index in self._seen[slot]
        self._seen[slot].add(proposer_index)
        return seen

    def is_known(self, slot: int, proposer_index: int) -> bool:
        return proposer_index in self._seen.get(slot, ())

    def prune(self, finalized_slot: int) -> None:
        for s in [s for s in self._seen if s < finalized_slot]:
            del self._seen[s]


class NaiveSyncAggregationPool:
    """Aggregate sync-committee messages per (slot, block_root,
    subcommittee) until aggregators collect them (reference:
    naive_aggregation_pool.rs SyncContributionAggregateMap)."""

    SLOTS_RETAINED = 3

    def __init__(self, subcommittee_size: int):
        self.subcommittee_size = subcommittee_size
        # (slot, root, subcommittee) -> (bits, AggregateSignature)
        self._map: dict[tuple, tuple] = {}

    def insert(self, slot: int, block_root: bytes, subcommittee: int,
               position: int, signature: bytes) -> None:
        key = (slot, bytes(block_root), subcommittee)
        sig = AggregateSignature.from_bytes(bytes(signature))
        entry = self._map.get(key)
        if entry is None:
            bits = [False] * self.subcommittee_size
            bits[position] = True
            self._map[key] = (bits, sig)
            return
        bits, agg = entry
        if bits[position]:
            return  # already contributed
        bits[position] = True
        agg.add_assign_aggregate(sig)

    def get(self, slot: int, block_root: bytes, subcommittee: int):
        return self._map.get((slot, bytes(block_root), subcommittee))

    def prune(self, current_slot: int) -> None:
        cutoff = current_slot - self.SLOTS_RETAINED
        self._map = {k: v for k, v in self._map.items() if k[0] >= cutoff}


class NaiveAggregationPool:
    """Aggregate unaggregated attestations per data root until the slot's
    aggregators collect them (reference: naive_aggregation_pool.rs)."""

    SLOTS_RETAINED = 3

    def __init__(self):
        # data_root -> (data, bits, AggregateSignature)
        self._map: dict[bytes, tuple] = {}

    def insert(self, attestation) -> None:
        root = attestation.data.hash_tree_root()
        bits = list(attestation.aggregation_bits)
        sig = AggregateSignature.from_bytes(bytes(attestation.signature))
        entry = self._map.get(root)
        if entry is None:
            self._map[root] = (attestation.data, bits, sig)
            return
        _, ebits, esig = entry
        if len(ebits) != len(bits):
            return
        if any(a and b for a, b in zip(ebits, bits)):
            return  # overlapping: drop (the op pool handles the general case)
        merged = [a or b for a, b in zip(ebits, bits)]
        esig.add_assign_aggregate(sig)
        self._map[root] = (entry[0], merged, esig)

    def get(self, data) -> tuple | None:
        return self._map.get(data.hash_tree_root())

    def get_by_root(self, data_root: bytes) -> tuple | None:
        return self._map.get(bytes(data_root))

    def prune(self, current_slot: int) -> None:
        cutoff = current_slot - self.SLOTS_RETAINED
        self._map = {
            r: e for r, e in self._map.items() if int(e[0].slot) >= cutoff
        }
