"""BeaconChain — the chain core (reference: beacon_node/beacon_chain).

Owns the store, op pool, fork choice, caches and the BLS backend choice,
and exposes the block/attestation pipelines
(beacon_chain.rs, block_verification.rs, attestation_verification.rs):

* block import as the typestate chain GossipVerifiedBlock →
  SignatureVerifiedBlock → ExecutionPendingBlock → import_block
  (block_verification.rs:567-596, beacon_chain.rs:2363,2511);
* attestation verification (single + batch with poisoning fallback, the
  north-star TPU workload — attestation_verification/batch.rs);
* block/attestation production for validators
  (produce_block_on_state:3144, produce_unaggregated_attestation);
* head tracking via fork choice (canonical_head.rs recompute_head_at_slot)
  with snapshot/shuffling/proposer caches and observed-* gossip guards;
* finalization side effects: store migration, cache pruning, fork-choice
  pruning (migrate.rs).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..consensus import helpers as h
from ..consensus import signature_sets as sigs
from ..consensus.config import ChainSpec, compute_signing_root
from ..consensus.transition.advance import complete_state_advance
from ..consensus.transition.block import (
    BlockProcessingError,
    SignatureStrategy,
    per_block_processing,
)
from ..consensus.transition.slot import process_slots
from ..consensus.types import (
    Checkpoint,
    spec_types,
    state_fork_name,
)
from ..crypto.bls import api as bls_api
from ..crypto.bls.api import AggregateSignature, SignatureSet, verify_signature_sets
from ..forkchoice import ExecutionStatus, ForkChoice
from ..oppool import OperationPool
from ..store.hot_cold import HotColdDB
from .caches import (
    BeaconProposerCache,
    NaiveAggregationPool,
    NaiveSyncAggregationPool,
    ObservedAggregates,
    ObservedAttesters,
    ObservedBlockProducers,
    ShufflingCache,
    SnapshotCache,
)
from .pubkey_cache import ValidatorPubkeyCache

ZERO_ROOT = b"\x00" * 32

# Gossip clock tolerance (reference: MAXIMUM_GOSSIP_CLOCK_DISPARITY 500ms,
# expressed here in slots for the deterministic clock).
FUTURE_SLOT_TOLERANCE = 1


class BlockError(ValueError):
    """(reference: block_verification.rs BlockError)"""


class AttestationError(ValueError):
    """(reference: attestation_verification.rs Error)"""


@dataclass
class HeadInfo:
    root: bytes
    block: object
    state: object


class BeaconChain:
    def __init__(
        self,
        spec: ChainSpec,
        store: HotColdDB,
        slot_clock,
        genesis_state,
        genesis_block,
        genesis_block_root: bytes,
        backend: str | None = None,
        pubkey_cache: ValidatorPubkeyCache | None = None,
    ):
        self.spec = spec
        self.store = store
        self.slot_clock = slot_clock
        self.backend = backend
        self.types = spec_types(spec.preset)
        # optional ExecutionLayer handle (reference: beacon_chain.execution_layer)
        self.execution_layer = None
        # validator_index -> fee-recipient hex, from the VC's
        # PreparationService (execution_layer proposer_preparation_data)
        self.proposer_preparations: dict[int, str] = {}
        from .validator_monitor import ValidatorMonitor

        self.validator_monitor = ValidatorMonitor()
        from ..consensus.cached_tree_hash import StateRootCache

        # incremental merkleization for the per-block state-root check
        # (reference: the state's tree_hash_cache)
        self.state_root_cache = StateRootCache()

        self.genesis_block_root = genesis_block_root
        self.genesis_validators_root = bytes(genesis_state.genesis_validators_root)

        self.op_pool = OperationPool(spec)
        # injectable for registry-scale startup (a device-table-backed
        # LAZY cache skips 1M host decompressions; pubkey_cache.py)
        self.pubkey_cache = (
            pubkey_cache if pubkey_cache is not None
            else ValidatorPubkeyCache.from_state(genesis_state, store=store.db)
        )
        self.shuffling_cache = ShufflingCache()
        self.snapshot_cache = SnapshotCache()
        self.proposer_cache = BeaconProposerCache()
        self.observed_attesters = ObservedAttesters()
        self.observed_aggregates = ObservedAggregates()
        self.observed_block_producers = ObservedBlockProducers()
        self.naive_aggregation_pool = NaiveAggregationPool()
        from ..consensus.config import SYNC_COMMITTEE_SUBNET_COUNT

        self.naive_sync_pool = NaiveSyncAggregationPool(
            spec.preset.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
        )
        # (slot, …) keyed observation sets, pruned per slot
        self.observed_sync_contributions: set = set()
        self.observed_sync_contributors: set = set()
        # sync-committee membership, cached per sync-committee period
        self._sync_members_cache: tuple[int, list[int]] | None = None

        self.fork_choice = ForkChoice.from_anchor(
            genesis_state,
            genesis_block_root,
            spec,
            balances_fn=self._justified_balances,
        )
        self._head = HeadInfo(genesis_block_root, genesis_block, genesis_state)
        self._finalized_checkpoint = (0, genesis_block_root)

    # ------------------------------------------------------------- factories
    @classmethod
    def from_genesis(
        cls, store: HotColdDB, genesis_state, spec: ChainSpec, slot_clock,
        backend=None, pubkey_cache=None,
    ) -> "BeaconChain":
        t = spec_types(spec.preset)
        fork = state_fork_name(genesis_state)
        state_root = genesis_state.hash_tree_root()
        block = t.BLOCK_BY_FORK[fork](state_root=state_root)
        signed = t.SIGNED_BLOCK_BY_FORK[fork](message=block)
        block_root = block.hash_tree_root()
        store.put_state(state_root, genesis_state)
        store.put_block(block_root, signed)
        store.set_genesis_block_root(block_root)
        chain = cls(
            spec, store, slot_clock, genesis_state, signed, block_root,
            backend, pubkey_cache=pubkey_cache,
        )
        chain.snapshot_cache.insert(block_root, genesis_state.copy())
        return chain

    # --------------------------------------------------------------- queries
    def current_slot(self) -> int:
        slot = self.slot_clock.now()
        return slot if slot is not None else 0

    def head(self) -> HeadInfo:
        return self._head

    def head_state_copy(self):
        return self._head.state.copy()

    def finalized_checkpoint(self) -> tuple[int, bytes]:
        return self._finalized_checkpoint

    def get_block(self, root: bytes):
        return self.store.get_block(root)

    def _justified_balances(self, checkpoint):
        """balances_fn for the fork-choice store: effective balances of
        active validators at the justified checkpoint's state."""
        epoch, root = checkpoint
        state = self._state_for_block_root(root)
        if state is None:
            state = self._head.state
        return [
            int(v.effective_balance) if h.is_active_validator(v, epoch) else 0
            for v in state.validators
        ]

    def _state_for_block_root(self, block_root: bytes):
        if block_root == self._head.root:
            return self._head.state
        snap = self.snapshot_cache.get_cloned(block_root)
        if snap is not None:
            return snap
        block = self.store.get_block(block_root)
        if block is None:
            return None
        return self.store.get_state(bytes(block.message.state_root))

    # ========================================================== block import
    def process_block(self, signed_block, *, block_delay_seconds=None) -> bytes:
        """Full import pipeline; returns the block root
        (reference: process_block:2363 → import_block:2511). Re-importing
        a known block is a benign no-op (BlockIsAlreadyKnown)."""
        block_root = signed_block.message.hash_tree_root()
        if self.fork_choice.contains_block(block_root):
            return block_root
        gossip = GossipVerifiedBlock(self, signed_block, block_root)
        pending = ExecutionPendingBlock(self, gossip)
        return self._import_block(pending, block_delay_seconds)

    def process_chain_segment(self, blocks) -> list[bytes]:
        """Import an ordered segment (reference: process_chain_segment:2215)."""
        return [self.process_block(b) for b in blocks]

    def _import_block(self, pending: "ExecutionPendingBlock", block_delay_seconds):
        signed_block = pending.signed_block
        block = signed_block.message
        block_root = pending.block_root
        state = pending.post_state

        state_root = bytes(block.state_root)
        ops_slot = self.current_slot()
        # Slot-lateness of the import relative to the block's own slot
        # (slot_clock_lateness_seconds{event="block_import"}).
        self.slot_clock.record_lateness("block_import", int(block.slot))
        self.fork_choice.on_block(
            max(ops_slot, int(block.slot)),
            block,
            block_root,
            state,
            block_delay_seconds=block_delay_seconds,
            execution_status=pending.execution_status,
            execution_block_hash=pending.execution_block_hash,
        )
        # only a fully-verified block claims its (slot, proposer) pair
        self.observed_block_producers.observe(
            int(block.slot), int(block.proposer_index)
        )
        self.store.put_block(block_root, signed_block)
        self.store.put_state(state_root, state)
        self.pubkey_cache.import_new_pubkeys(state)
        self.snapshot_cache.insert(block_root, state.copy())

        # feed fork choice the block's own attestations (on_attestation
        # with is_from_block, reference: import_block)
        from ..forkchoice.fork_choice import ForkChoiceError

        for att in block.body.attestations:
            try:
                indexed = h.get_indexed_attestation(state, att, self.spec)
                self.fork_choice.on_attestation(
                    max(ops_slot, int(block.slot)), indexed, is_from_block=True
                )
                self.validator_monitor.observe_block_attestation_indices(
                    att, indexed.attesting_indices, int(block.slot)
                )
            except (ValueError, ForkChoiceError):
                continue

        self.validator_monitor.observe_block(block, block_root, self.spec)
        self.recompute_head()
        return block_root

    def recompute_head(self) -> bytes:
        """(reference: canonical_head.rs recompute_head_at_slot:431)"""
        slot = max(self.current_slot(), int(self._head.state.slot))
        head_root = self.fork_choice.get_head(slot)
        if head_root != self._head.root:
            block = self.store.get_block(head_root)
            state = self._state_for_block_root(head_root)
            if state is None:
                raise BlockError("head state missing from store")
            if int(state.slot) < int(block.message.slot):
                raise BlockError("head state behind head block")
            self._head = HeadInfo(head_root, block, state)
            self._notify_forkchoice_updated()
        self._check_finalization()
        return self._head.root

    def _notify_forkchoice_updated(self) -> None:
        """forkchoiceUpdated to the engine on head change
        (canonical_head.rs → execution_layer)."""
        el = self.execution_layer
        if el is None:
            return
        body = self._head.block.message.body
        payload = getattr(body, "execution_payload", None)
        if payload is None or bytes(payload.block_hash) == ZERO_ROOT:
            return
        _, finalized_root = self._finalized_checkpoint
        finalized_hash = b"\x00" * 32
        fin_block = self.store.get_block(finalized_root)
        if fin_block is not None:
            fin_payload = getattr(
                fin_block.message.body, "execution_payload", None
            )
            if fin_payload is not None:
                finalized_hash = bytes(fin_payload.block_hash)
        try:
            status, _ = el.notify_forkchoice_updated(
                bytes(payload.block_hash), finalized_hash
            )
            if status == ExecutionStatus.VALID:
                self.fork_choice.on_valid_execution_payload(self._head.root)
        except Exception:  # lhtpu: ignore[LH502] -- execution engine offline is an expected steady state; chain stays optimistic
            pass  # engine offline: stay optimistic (engines.rs fallback)

    def _check_finalization(self) -> None:
        finalized = self.fork_choice.store.finalized_checkpoint
        if finalized[0] > self._finalized_checkpoint[0]:
            self._finalized_checkpoint = finalized
            p = self.spec.preset
            finalized_epoch, finalized_root = finalized
            # prune gossip observation sets + fork choice + op pool
            self.observed_attesters.prune(finalized_epoch)
            self.observed_aggregates.prune(finalized_epoch)
            self.observed_block_producers.prune(finalized_epoch * p.SLOTS_PER_EPOCH)
            self.validator_monitor.prune(finalized_epoch)
            self.fork_choice.prune()
            self.op_pool.prune(self._head.state)
            # migrate finalized history into the freezer
            block = self.store.get_block(finalized_root)
            if block is not None:
                state = self._state_for_block_root(finalized_root)
                if state is not None:
                    target = finalized_epoch * p.SLOTS_PER_EPOCH
                    if int(state.slot) < target:
                        state = complete_state_advance(
                            state.copy(), None, target, self.spec
                        )
                    if int(state.slot) % p.SLOTS_PER_EPOCH == 0:
                        try:
                            self.store.migrate(state, finalized_root)
                        except Exception:  # lhtpu: ignore[LH502] -- freezer migration is best-effort background work; hot store remains authoritative
                            pass  # migration is best-effort background work

    # ====================================================== block production
    def produce_block(
        self, randao_reveal: bytes, slot: int | None = None, graffiti: bytes = b""
    ):
        """Build an unsigned block on the head state
        (reference: produce_block_on_state:3144)."""
        t = self.types
        p = self.spec.preset
        head = self._head
        slot = slot if slot is not None else self.current_slot()
        state = head.state.copy()
        if int(state.slot) < slot:
            state = complete_state_advance(state, None, slot, self.spec)
        elif int(state.slot) > slot:
            raise BlockError("cannot produce a block behind the head state")

        fork = state_fork_name(state)
        proposer_index = h.get_beacon_proposer_index(state, self.spec)
        caches: dict = {}
        attestations = self.op_pool.get_attestations(state, caches)
        proposer_slashings, attester_slashings = self.op_pool.get_slashings(state)
        voluntary_exits = self.op_pool.get_voluntary_exits(state)

        body_kwargs = dict(
            randao_reveal=randao_reveal,
            eth1_data=state.eth1_data,
            graffiti=graffiti.ljust(32, b"\x00")[:32],
            proposer_slashings=proposer_slashings,
            attester_slashings=attester_slashings,
            attestations=attestations,
            deposits=[],
            voluntary_exits=voluntary_exits,
        )
        if fork in ("altair", "bellatrix"):
            body_kwargs["sync_aggregate"] = self.op_pool.get_sync_aggregate(
                slot - 1, head.root
            )
        if fork == "bellatrix":
            body_kwargs["execution_payload"] = self._produce_execution_payload(
                state, slot
            )
        body = t.BODY_BY_FORK[fork](**body_kwargs)

        block = t.BLOCK_BY_FORK[fork](
            slot=slot,
            proposer_index=proposer_index,
            parent_root=head.root,
            state_root=ZERO_ROOT,
            body=body,
        )
        # dry-run the transition to fill in the state root
        trial = t.SIGNED_BLOCK_BY_FORK[fork](message=block)
        per_block_processing(
            state,
            trial,
            self.spec,
            strategy=SignatureStrategy.NO_VERIFICATION,
            get_pubkey=self.pubkey_cache.as_getter(),
            caches=caches,
        )
        block.state_root = self.state_root_cache.state_root(state)
        return block, state

    def _produce_execution_payload(self, state, slot: int):
        """Real payload via the engine when the merge is complete, else
        the empty pre-transition payload (execution_payload.rs
        get_execution_payload)."""
        t = self.types
        el = self.execution_layer
        from ..consensus.transition.block import is_merge_transition_complete

        if el is None or not is_merge_transition_complete(state, self.spec):
            return t.ExecutionPayload()
        from ..consensus import helpers as h2
        from ..execution.execution_layer import engine_json_to_payload

        parent_hash = bytes(state.latest_execution_payload_header.block_hash)
        epoch = slot // self.spec.preset.SLOTS_PER_EPOCH
        proposer = h2.get_beacon_proposer_index(state, self.spec)
        fee_recipient = self.proposer_preparations.get(
            proposer, "0x" + "00" * 20
        )
        attributes = {
            "timestamp": hex(
                int(state.genesis_time) + slot * self.spec.SECONDS_PER_SLOT
            ),
            "prevRandao": "0x" + bytes(
                h2.get_randao_mix(state, epoch, self.spec)
            ).hex(),
            "suggestedFeeRecipient": fee_recipient,
        }
        _, finalized_root = self._finalized_checkpoint
        finalized_hash = b"\x00" * 32
        fin_block = self.store.get_block(finalized_root)
        if fin_block is not None:
            fin_payload = getattr(fin_block.message.body, "execution_payload", None)
            if fin_payload is not None:
                finalized_hash = bytes(fin_payload.block_hash)
        _, payload_id = el.notify_forkchoice_updated(
            parent_hash, finalized_hash, payload_attributes=attributes
        )
        if payload_id is None:
            raise BlockError("engine did not return a payload id")
        return engine_json_to_payload(t, el.get_payload(payload_id))

    # ================================================ attestation production
    def produce_unaggregated_attestation(self, slot: int, committee_index: int):
        """(reference: produce_unaggregated_attestation, served from the
        attester caches)"""
        t = self.types
        p = self.spec.preset
        head = self._head
        state = head.state
        if int(state.slot) < slot:
            state = complete_state_advance(state.copy(), None, slot, self.spec)
        epoch = slot // p.SLOTS_PER_EPOCH
        committee = self._committee_at(state, slot, committee_index, epoch)

        # Target = block root at the epoch-start slot. When attesting AT
        # the boundary slot the state hasn't recorded that root yet — the
        # head block is the boundary block (or latest before a skip).
        target_slot = epoch * p.SLOTS_PER_EPOCH
        if target_slot >= int(state.slot):
            target_root = head.root
        else:
            target_root = bytes(h.get_block_root_at_slot(state, target_slot, self.spec))
        from ..consensus.types import AttestationData

        data = AttestationData(
            slot=slot,
            index=committee_index,
            beacon_block_root=head.root,
            source=state.current_justified_checkpoint,
            target=Checkpoint(epoch=epoch, root=target_root),
        )
        return t.Attestation(
            aggregation_bits=[False] * len(committee),
            data=data,
            signature=b"\xc0" + bytes(95),
        )

    def _committee_at(self, state, slot: int, index: int, epoch: int):
        cache = self.shuffling_cache.get_or_init(
            state, epoch, self._shuffling_decision_root(epoch), self.spec
        )
        return cache.get_beacon_committee(slot, index)

    def _shuffling_decision_root(self, epoch: int) -> bytes:
        """Attester shuffling for ``epoch`` is decided by the block at the
        last slot of ``epoch - 2`` on the head chain (reference:
        BeaconState::attester_shuffling_decision_root)."""
        p = self.spec.preset
        decision_slot = max(epoch - 1, 0) * p.SLOTS_PER_EPOCH - 1
        if decision_slot < 0:
            return self.genesis_block_root
        root = self.fork_choice.proto.ancestor_at_slot(self._head.root, decision_slot)
        return root if root is not None else self.genesis_block_root

    # ================================================ attestation verification
    def verify_unaggregated_attestation_for_gossip(self, attestation):
        """(reference: attestation_verification.rs
        IndexedUnaggregatedAttestation::verify + signature check)"""
        indexed, committee = self._gossip_attestation_checks(attestation)
        if sum(attestation.aggregation_bits) != 1:
            raise AttestationError("unaggregated attestation must set one bit")
        validator_index = int(indexed.attesting_indices[0])
        epoch = int(attestation.data.target.epoch)
        if self.observed_attesters.is_known(epoch, validator_index):
            raise AttestationError("duplicate attestation (prior seen)")

        sig_set = sigs.indexed_attestation_signature_set(
            self._head.state,
            self.pubkey_cache.as_getter(),
            attestation.signature,
            indexed,
            self.spec,
        )
        if not verify_signature_sets([sig_set], backend=self.backend):
            raise AttestationError("invalid attestation signature")
        self.observed_attesters.observe(epoch, validator_index)
        return VerifiedAttestation(attestation, indexed)

    def batch_verify_unaggregated_attestations_for_gossip(self, attestations):
        """Batch path with poisoning fallback — the TPU hot loop
        (reference: batch_verify_unaggregated_attestations, batch.rs:130-210)."""
        candidates = []
        # Timed read lock over batch assembly (reference: batch.rs:63-66
        # VALIDATOR_PUBKEY_CACHE_LOCK_TIMEOUT): registry imports on the
        # block-import path cannot silently stall gossip verification. A
        # timeout fails the BATCH (each attestation gets a retryable
        # error, mirroring the reference's BeaconChainError), never the
        # caller's drive loop.
        from ..common.timeout_lock import LockTimeout

        try:
            lock_ctx = self.pubkey_cache.lock.read()
            lock_ctx.__enter__()
        except LockTimeout:
            err = AttestationError("pubkey cache lock timeout")
            return [err for _ in attestations]
        try:
            for att in attestations:
                try:
                    indexed, _ = self._gossip_attestation_checks(att)
                    if sum(att.aggregation_bits) != 1:
                        raise AttestationError("unaggregated attestation must set one bit")
                    vi = int(indexed.attesting_indices[0])
                    epoch = int(att.data.target.epoch)
                    if self.observed_attesters.is_known(epoch, vi):
                        raise AttestationError("duplicate attestation (prior seen)")
                    sig_set = sigs.indexed_attestation_signature_set(
                        self._head.state,
                        self.pubkey_cache.as_getter(),
                        att.signature,
                        indexed,
                        self.spec,
                    )
                    candidates.append((att, indexed, vi, epoch, sig_set, None))
                except (AttestationError, ValueError) as e:
                    candidates.append((att, None, None, None, None, e))
        finally:
            lock_ctx.__exit__(None, None, None)

        sets = [c[4] for c in candidates if c[4] is not None]
        oks = self._bisect_verify(sets)
        results = []
        it = iter(oks)
        for att, indexed, vi, epoch, sig_set, err in candidates:
            if err is not None:
                results.append(err)
                continue
            if next(it):
                # Dedup AFTER verification (exactly like the sequential
                # path): the first VERIFIED attestation per (epoch,
                # attester) wins; later intra-batch duplicates or
                # equivocations are rejected, and an earlier
                # invalid-signature copy cannot censor a valid one.
                if self.observed_attesters.is_known(epoch, vi):
                    results.append(
                        AttestationError("duplicate attestation (prior seen)")
                    )
                    continue
                self.observed_attesters.observe(epoch, vi)
                results.append(VerifiedAttestation(att, indexed))
            else:
                results.append(AttestationError("invalid attestation signature"))
        return results

    def batch_verify_aggregated_attestations_for_gossip(
        self, signed_aggregates
    ):
        """Batch path for SignedAggregateAndProof gossip: every
        aggregate's THREE signature sets (selection proof, aggregator,
        aggregate) ride one device batch with poisoning bisection —
        the aggregate twin of the unaggregated batch pipeline
        (reference: attestation_verification/batch.rs:36-128
        batch_verify_aggregated_attestations). Pre-verification checks
        (dedup roots/aggregators, is_aggregator) keep the sequential
        path's semantics exactly; an aggregate passes only if all three
        of its sets verify."""
        from ..common.timeout_lock import LockTimeout

        candidates = []
        try:
            lock_ctx = self.pubkey_cache.lock.read()
            lock_ctx.__enter__()
        except LockTimeout:
            err = AttestationError("pubkey cache lock timeout")
            return [err for _ in signed_aggregates]
        try:
            state = self._head.state
            get_pubkey = self.pubkey_cache.as_getter()
            for sa in signed_aggregates:
                try:
                    message = sa.message
                    aggregate = message.aggregate
                    indexed, committee = self._gossip_attestation_checks(
                        aggregate
                    )
                    epoch = int(aggregate.data.target.epoch)
                    att_root = aggregate.hash_tree_root()
                    # CHECK-only here; recording happens after the batch
                    # verifies (like the unaggregated path) so an
                    # invalid-signature copy cannot censor the valid
                    # aggregate from an honest aggregator.
                    if self.observed_aggregates.is_known_root(epoch, att_root):
                        raise AttestationError("aggregate already known")
                    aggregator_index = int(message.aggregator_index)
                    if self.observed_aggregates.is_known_aggregator(
                        epoch, aggregator_index
                    ):
                        raise AttestationError(
                            "aggregator already seen this epoch"
                        )
                    if not self._is_aggregator(
                        int(aggregate.data.slot),
                        len(committee),
                        bytes(message.selection_proof),
                    ):
                        raise AttestationError("validator is not an aggregator")
                    three = [
                        sigs.signed_aggregate_selection_proof_signature_set(
                            state, get_pubkey, sa, self.spec
                        ),
                        sigs.signed_aggregate_signature_set(
                            state, get_pubkey, sa, self.spec
                        ),
                        sigs.indexed_attestation_signature_set(
                            state, get_pubkey, aggregate.signature, indexed,
                            self.spec,
                        ),
                    ]
                    candidates.append(
                        (aggregate, indexed, three, epoch, att_root,
                         aggregator_index, None)
                    )
                except (AttestationError, ValueError) as e:
                    candidates.append((None, None, None, None, None, None, e))
        finally:
            lock_ctx.__exit__(None, None, None)

        sets = [s for c in candidates if c[2] is not None for s in c[2]]
        oks = iter(self._bisect_verify(sets))
        results = []
        for (aggregate, indexed, three, epoch, att_root, agg_idx,
             err) in candidates:
            if err is not None:
                results.append(err)
                continue
            ok = all([next(oks), next(oks), next(oks)])  # no short-circuit:
            # the iterator must advance exactly 3 per aggregate
            if not ok:
                results.append(
                    AttestationError("invalid aggregate signature(s)")
                )
                continue
            # Dedup AFTER verification (first VERIFIED copy wins —
            # covers intra-batch duplicates too).
            if self.observed_aggregates.observe_root(epoch, att_root):
                results.append(AttestationError("aggregate already known"))
                continue
            if self.observed_aggregates.observe_aggregator(epoch, agg_idx):
                results.append(
                    AttestationError("aggregator already seen this epoch")
                )
                continue
            results.append(VerifiedAttestation(aggregate, indexed))
        return results

    # Host-bisection policy constants, kept as aliases of the hoisted
    # crypto/bls/api values (ISSUE 5 moved the budgeted bisection there
    # so the backend's degraded-triage route shares it).
    _BISECT_LINEAR_CUTOFF = bls_api.BISECT_LINEAR_CUTOFF
    _BISECT_WORK_BUDGET = bls_api.BISECT_WORK_BUDGET

    def _bisect_verify(self, sets) -> list[bool]:
        """Per-set verdicts for a poisoned batch (SURVEY §7.1 hard part
        #3). ISSUE 5: routes through verify_signature_sets_triaged —
        backends with grouped device verdicts (jax) isolate the invalid
        sets by slicing already-packed device inputs in O(log_G
        poisoned-groups) dispatches; backends without the capability
        (python/fake/native) fall back to the budgeted halving bisection
        (api.bisect_verify_sets), the pre-triage strategy, with k
        poisoned lanes costing O(k·log n) verifier calls instead of the
        reference's n individual re-verifications
        (attestation_verification/batch.rs falls back to per-set)."""
        return bls_api.verify_signature_sets_triaged(
            sets, backend=self.backend
        )

    def _bisect_verify_budgeted(self, sets, budget) -> list[bool]:
        """Budgeted halving bisection (compatibility wrapper over the
        hoisted api.bisect_verify_sets — same verdicts, same call
        structure)."""
        return bls_api.bisect_verify_sets(
            sets, backend=self.backend, budget=budget
        )

    def _gossip_attestation_checks(self, attestation):
        data = attestation.data
        p = self.spec.preset
        current_slot = self.current_slot()
        if int(data.slot) > current_slot + FUTURE_SLOT_TOLERANCE:
            raise AttestationError("attestation from the future")
        if int(data.slot) + p.SLOTS_PER_EPOCH < current_slot:
            raise AttestationError("attestation too old")
        if int(data.target.epoch) != int(data.slot) // p.SLOTS_PER_EPOCH:
            raise AttestationError("target epoch does not match slot")
        if not self.fork_choice.contains_block(bytes(data.beacon_block_root)):
            raise AttestationError("unknown head block")
        if not self.fork_choice.contains_block(bytes(data.target.root)):
            raise AttestationError("unknown target block")

        state = self._head.state
        epoch = int(data.target.epoch)
        committee = self._committee_at(state, int(data.slot), int(data.index), epoch)
        if len(attestation.aggregation_bits) != len(committee):
            raise AttestationError("bitfield/committee length mismatch")
        indexed = self.types.IndexedAttestation(
            attesting_indices=sorted(
                int(v)
                for v, bit in zip(committee, attestation.aggregation_bits)
                if bit
            ),
            data=data,
            signature=attestation.signature,
        )
        return indexed, committee

    def verify_aggregated_attestation_for_gossip(self, signed_aggregate):
        """Three signature sets: selection proof, aggregator, aggregate
        (reference: attestation_verification.rs aggregate flow)."""
        message = signed_aggregate.message
        aggregate = message.aggregate
        indexed, committee = self._gossip_attestation_checks(aggregate)
        epoch = int(aggregate.data.target.epoch)
        att_root = aggregate.hash_tree_root()
        if self.observed_aggregates.observe_root(epoch, att_root):
            raise AttestationError("aggregate already known")
        aggregator_index = int(message.aggregator_index)
        if self.observed_aggregates.observe_aggregator(epoch, aggregator_index):
            raise AttestationError("aggregator already seen this epoch")
        if not self._is_aggregator(
            int(aggregate.data.slot),
            len(committee),
            bytes(message.selection_proof),
        ):
            raise AttestationError("validator is not an aggregator")

        state = self._head.state
        get_pubkey = self.pubkey_cache.as_getter()
        sets = [
            sigs.signed_aggregate_selection_proof_signature_set(
                state, get_pubkey, signed_aggregate, self.spec
            ),
            sigs.signed_aggregate_signature_set(
                state, get_pubkey, signed_aggregate, self.spec
            ),
            sigs.indexed_attestation_signature_set(
                state, get_pubkey, aggregate.signature, indexed, self.spec
            ),
        ]
        if not verify_signature_sets(sets, backend=self.backend):
            raise AttestationError("invalid aggregate signature(s)")
        return VerifiedAttestation(aggregate, indexed)

    def _is_aggregator(self, slot, committee_len, selection_proof: bytes) -> bool:
        return h.is_aggregator(committee_len, selection_proof, self.spec)

    def apply_attestation_to_fork_choice(self, verified: "VerifiedAttestation"):
        self.fork_choice.on_attestation(
            self.current_slot(), verified.indexed, is_from_block=False
        )
        self.validator_monitor.observe_gossip_attestation(
            verified.indexed, self.current_slot(), self.spec
        )

    def add_to_naive_aggregation_pool(self, verified: "VerifiedAttestation"):
        self.naive_aggregation_pool.insert(verified.attestation)

    def add_to_operation_pool(self, verified: "VerifiedAttestation"):
        self.op_pool.insert_attestation(verified.attestation)

    # ====================================== sync committee verification
    def _sync_committee_members(self, state) -> list[int]:
        """Cached current-sync-committee validator indices: the
        committee is stable for EPOCHS_PER_SYNC_COMMITTEE_PERIOD, so
        resolve the O(registry) pubkey mapping once per period."""
        p = self.spec.preset
        period = h.get_current_epoch(state, self.spec) // (
            p.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
        )
        cached = self._sync_members_cache
        if cached is not None and cached[0] == period:
            return cached[1]
        members = h.current_sync_committee_indices(state, self.spec)
        self._sync_members_cache = (period, members)
        return members

    def verify_sync_committee_message_for_gossip(self, message):
        """(reference: sync_committee_verification.rs
        verify_sync_committee_message_for_gossip)"""
        state = self._head.state
        if state_fork_name(state) == "phase0":
            raise AttestationError("sync committees require altair")
        slot = int(message.slot)
        current = self.current_slot()
        if not (current - 1 <= slot <= current + FUTURE_SLOT_TOLERANCE):
            raise AttestationError("sync message outside the current slot window")
        vi = int(message.validator_index)
        members = self._sync_committee_members(state)
        if vi not in members:
            raise AttestationError("validator not in the current sync committee")
        key = (slot, vi)
        if key in self.observed_sync_contributors:
            raise AttestationError("duplicate sync message for slot")
        sig_set = sigs.sync_committee_message_set(
            state, self.pubkey_cache.as_getter(), message, self.spec
        )
        if not verify_signature_sets([sig_set], backend=self.backend):
            raise AttestationError("invalid sync message signature")
        self.observed_sync_contributors.add(key)
        return message

    def sync_subnets_for_validator(self, validator_index: int) -> set[int]:
        """Subnets this committee member's positions map onto (the
        gossip topic routing for its messages)."""
        from ..consensus.config import SYNC_COMMITTEE_SUBNET_COUNT

        members = self._sync_committee_members(self._head.state)
        size = self.spec.preset.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
        return {
            position // size
            for position, member in enumerate(members)
            if member == int(validator_index)
        }

    def add_to_naive_sync_pool(self, message) -> None:
        from ..consensus.config import SYNC_COMMITTEE_SUBNET_COUNT

        state = self._head.state
        members = self._sync_committee_members(state)
        size = self.spec.preset.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
        vi = int(message.validator_index)
        for position, member in enumerate(members):
            if member == vi:
                self.naive_sync_pool.insert(
                    int(message.slot),
                    bytes(message.beacon_block_root),
                    position // size,
                    position % size,
                    bytes(message.signature),
                )

    def produce_sync_contribution(self, slot: int, block_root: bytes,
                                  subcommittee_index: int):
        entry = self.naive_sync_pool.get(slot, block_root, subcommittee_index)
        if entry is None:
            return None
        bits, sig = entry
        return self.types.SyncCommitteeContribution(
            slot=slot,
            beacon_block_root=bytes(block_root),
            subcommittee_index=subcommittee_index,
            aggregation_bits=list(bits),
            signature=sig.to_bytes(),
        )

    def verify_sync_contribution_for_gossip(self, signed_contribution):
        """Three sets: selection proof, aggregator signature, contribution
        aggregate (reference: sync_committee_verification.rs:618 batch)."""
        message = signed_contribution.message
        contribution = message.contribution
        state = self._head.state
        if state_fork_name(state) == "phase0":
            raise AttestationError("sync committees require altair")
        slot = int(contribution.slot)
        current = self.current_slot()
        if not (current - 1 <= slot <= current + FUTURE_SLOT_TOLERANCE):
            raise AttestationError("contribution outside the slot window")
        # the aggregator must itself sit in the target subcommittee
        # (reference: AggregatorNotInCommittee)
        if int(contribution.subcommittee_index) not in (
            self.sync_subnets_for_validator(int(message.aggregator_index))
        ):
            raise AttestationError("aggregator not in the subcommittee")
        if not h.is_sync_committee_aggregator(
            bytes(message.selection_proof), self.spec
        ):
            raise AttestationError("invalid sync aggregator selection")
        key = (slot, int(contribution.subcommittee_index),
               contribution.hash_tree_root())
        if key in self.observed_sync_contributions:
            raise AttestationError("contribution already known")
        from ..consensus.config import SYNC_COMMITTEE_SUBNET_COUNT

        all_members = self._sync_committee_members(state)
        size = self.spec.preset.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
        start = int(contribution.subcommittee_index) * size
        members = all_members[start : start + size]
        participants = [
            m for m, bit in zip(members, contribution.aggregation_bits) if bit
        ]
        get_pubkey = self.pubkey_cache.as_getter()
        sets = [
            sigs.sync_committee_selection_proof_signature_set(
                state, get_pubkey, message, self.spec
            ),
            sigs.signed_contribution_and_proof_signature_set(
                state, get_pubkey, signed_contribution, self.spec
            ),
        ]
        contrib_set = sigs.sync_committee_contribution_signature_set(
            state, get_pubkey, contribution, participants, self.spec
        )
        if contrib_set is not None:
            sets.append(contrib_set)
        if not verify_signature_sets(sets, backend=self.backend):
            raise AttestationError("invalid sync contribution signature(s)")
        self.observed_sync_contributions.add(key)
        self.op_pool.insert_sync_contribution(contribution)
        return signed_contribution

    # ------------------------------------------------------------ persistence
    def persist(self) -> None:
        """Write head/fork-choice/op-pool to the store so a restart
        resumes exactly here (persist_head/persist_fork_choice)."""
        from .persistence import save_chain

        save_chain(self)

    @classmethod
    def from_store(cls, store, spec, slot_clock, backend=None) -> "BeaconChain":
        """Resume from a persisted store (ClientGenesis::FromStore)."""
        from .persistence import load_chain

        return load_chain(store, spec, slot_clock, backend=backend)

    # ------------------------------------------------------------ slot tasks
    def per_slot_task(self) -> None:
        """(reference: beacon_chain.rs per_slot_task via timer)"""
        slot = self.current_slot()
        self.naive_aggregation_pool.prune(slot)
        self.naive_sync_pool.prune(slot)
        # sync observation sets are (slot, …)-keyed; retain a short window
        cutoff = slot - 3
        self.observed_sync_contributors = {
            k for k in self.observed_sync_contributors if k[0] >= cutoff
        }
        self.observed_sync_contributions = {
            k for k in self.observed_sync_contributions if k[0] >= cutoff
        }
        self.fork_choice.update_time(slot)


class VerifiedAttestation:
    __slots__ = ("attestation", "indexed")

    def __init__(self, attestation, indexed):
        self.attestation = attestation
        self.indexed = indexed


# ---------------------------------------------------------------- typestates


class GossipVerifiedBlock:
    """Cheap structural checks before the expensive pipeline
    (reference: block_verification.rs:638 GossipVerifiedBlock::new)."""

    def __init__(self, chain: BeaconChain, signed_block, block_root=None):
        self.signed_block = signed_block
        block = signed_block.message
        spec = chain.spec
        current_slot = chain.current_slot()

        if int(block.slot) > current_slot + FUTURE_SLOT_TOLERANCE:
            raise BlockError("block from the future")
        finalized_epoch, _ = chain.finalized_checkpoint()
        if int(block.slot) <= finalized_epoch * spec.preset.SLOTS_PER_EPOCH:
            raise BlockError("block older than finalization")
        parent_root = bytes(block.parent_root)
        if not chain.fork_choice.contains_block(parent_root):
            raise BlockError("unknown parent block")
        expected_fork = spec.fork_name_at_epoch(
            int(block.slot) // spec.preset.SLOTS_PER_EPOCH
        )
        if type(block).fork != expected_fork:
            raise BlockError(
                f"wrong fork: block {type(block).fork}, schedule {expected_fork}"
            )
        # check-only: recording happens post-verification in import_block
        if chain.observed_block_producers.is_known(
            int(block.slot), int(block.proposer_index)
        ):
            raise BlockError("proposer equivocation: slot already seen")

        self.block_root = (
            block_root if block_root is not None else block.hash_tree_root()
        )
        self.chain = chain


class ExecutionPendingBlock:
    """State transition + full signature verification
    (reference: block_verification.rs:1038 + SignatureVerifiedBlock)."""

    def __init__(self, chain: BeaconChain, gossip: GossipVerifiedBlock):
        signed_block = gossip.signed_block
        block = signed_block.message
        parent_root = bytes(block.parent_root)

        pre_state = chain.snapshot_cache.get_cloned(parent_root)
        if pre_state is None:
            pre_state = chain._state_for_block_root(parent_root)
        if pre_state is None:
            raise BlockError("missing pre-state for parent")
        state = pre_state.copy() if pre_state is chain._head.state else pre_state

        if int(state.slot) > int(block.slot):
            raise BlockError("parent state ahead of block")
        state = process_slots(state, int(block.slot), chain.spec)

        # expected proposer
        expected_proposer = h.get_beacon_proposer_index(state, chain.spec)
        if int(block.proposer_index) != expected_proposer:
            raise BlockError(
                f"wrong proposer: block {block.proposer_index}, "
                f"expected {expected_proposer}"
            )

        # full transition; ONE bulk signature batch incl. the proposal
        # (on the TPU backend: one fused multi-pairing per block)
        try:
            per_block_processing(
                state,
                signed_block,
                chain.spec,
                strategy=SignatureStrategy.VERIFY_BULK,
                get_pubkey=chain.pubkey_cache.as_getter(),
                backend=chain.backend,
            )
        except BlockProcessingError as e:
            raise BlockError(f"state transition failed: {e}") from e

        computed_root = chain.state_root_cache.state_root(state)
        if computed_root != bytes(block.state_root):
            raise BlockError("state root mismatch")

        self.signed_block = signed_block
        self.block_root = gossip.block_root
        self.post_state = state
        fork = state_fork_name(state)
        if fork == "bellatrix" and hasattr(block.body, "execution_payload"):
            from ..consensus.transition.block import is_execution_enabled

            if is_execution_enabled(state, block.body, chain.spec):
                payload = block.body.execution_payload
                self.execution_block_hash = bytes(payload.block_hash)
                if chain.execution_layer is not None:
                    # verify with the engine (execution_payload.rs
                    # notify_new_payload); INVALID payloads kill the block
                    from ..execution.execution_layer import payload_to_engine_json

                    status = chain.execution_layer.notify_new_payload(
                        payload_to_engine_json(payload)
                    )
                    if status == ExecutionStatus.INVALID:
                        raise BlockError("execution payload invalid")
                    self.execution_status = status
                else:
                    self.execution_status = ExecutionStatus.OPTIMISTIC
            else:
                self.execution_status = ExecutionStatus.IRRELEVANT
                self.execution_block_hash = None
        else:
            self.execution_status = ExecutionStatus.IRRELEVANT
            self.execution_block_hash = None
