"""State-advance timer (reference: beacon_chain/src/state_advance_timer.rs:89).

Three-quarters through each slot the node pre-computes the head state
advanced to the NEXT slot and plants it in the snapshot cache, so block
production at the next slot start and attestation verification against
next-slot shufflings skip the epoch/slot processing latency. The
reference guards against re-advancing (lock) and only advances within
one slot of the head; both carried over.
"""

from __future__ import annotations

from ..common.metrics import REGISTRY
from ..consensus.transition.advance import complete_state_advance


class StateAdvanceTimer:
    def __init__(self, chain):
        self.chain = chain
        self._advanced_for: bytes | None = None  # head root last advanced
        self._m = REGISTRY.counter(
            "state_advance_runs_total", "Pre-emptive state advances", ("outcome",)
        )

    def due(self) -> bool:
        """True in the last quarter of the current slot."""
        frac = self.chain.slot_clock.seconds_from_current_slot_start()
        if frac is None:
            return False
        return frac >= 0.75 * self.chain.slot_clock.seconds_per_slot

    def run(self) -> bool:
        """Advance head state to next slot into the snapshot cache
        (state_advance_timer.rs advance_head)."""
        chain = self.chain
        head = chain.head()
        if self._advanced_for == head.root:
            self._m.inc(outcome="already_advanced")
            return False
        next_slot = chain.current_slot() + 1
        if int(head.state.slot) >= next_slot:
            self._m.inc(outcome="head_ahead")
            return False
        try:
            # COMPLETE advance (real state roots): the snapshot cache
            # feeds block import, which must see exact roots
            advanced = complete_state_advance(
                head.state.copy(), None, next_slot, chain.spec
            )
        except Exception:
            self._m.inc(outcome="error")
            return False
        chain.snapshot_cache.insert(head.root, advanced)
        self._advanced_for = head.root
        self._m.inc(outcome="success")
        return True
