"""ValidatorMonitor — per-validator liveness/performance introspection
(reference: beacon_chain/src/validator_monitor.rs, 1.5k LoC).

Operators register validator indices (or auto-register all); the chain
feeds every imported block and verified attestation through the
monitor, which tracks per-validator per-epoch: blocks proposed,
attestations seen (gossip vs in-block), inclusion delay, hit/miss
summaries — surfaced as metrics and on-demand reports (the reference
additionally logs per-event; here the structured logger hook is
optional).
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass, field

from ..common.metrics import REGISTRY


@dataclass
class EpochSummary:
    """(validator_monitor.rs EpochSummary)"""

    attestations_seen: int = 0
    attestation_min_delay_slots: int | None = None
    attestations_in_block: int = 0
    min_inclusion_delay: int | None = None
    blocks_proposed: int = 0
    sync_messages_seen: int = 0


class ValidatorMonitor:
    def __init__(self, auto_register: bool = False, log=None):
        self.auto_register = auto_register
        self.log = log
        self._watched: set[int] = set()
        # validator -> epoch -> summary
        self.summaries: dict[int, dict[int, EpochSummary]] = defaultdict(
            lambda: defaultdict(EpochSummary)
        )
        self._m_atts = REGISTRY.counter(
            "validator_monitor_attestations_total",
            "Attestations observed for watched validators", ("src",),
        )
        self._m_blocks = REGISTRY.counter(
            "validator_monitor_blocks_total",
            "Blocks proposed by watched validators",
        )

    # ---------------------------------------------------------- registration
    def register_validator(self, index: int) -> None:
        self._watched.add(int(index))

    def watched(self, index: int) -> bool:
        return self.auto_register or int(index) in self._watched

    # ------------------------------------------------------------ ingestion
    def observe_gossip_attestation(self, indexed, seen_slot: int, spec) -> None:
        epoch = int(indexed.data.target.epoch)
        delay = max(0, seen_slot - int(indexed.data.slot))
        for vi in indexed.attesting_indices:
            vi = int(vi)
            if not self.watched(vi):
                continue
            s = self.summaries[vi][epoch]
            s.attestations_seen += 1
            if (
                s.attestation_min_delay_slots is None
                or delay < s.attestation_min_delay_slots
            ):
                s.attestation_min_delay_slots = delay
            self._m_atts.inc(src="gossip")
            if self.log is not None:
                self.log.debug(
                    "attestation seen", validator=vi, epoch=epoch, delay=delay
                )

    def observe_block(self, block, block_root: bytes, spec) -> None:
        proposer = int(block.proposer_index)
        p = spec.preset
        if self.watched(proposer):
            epoch = int(block.slot) // p.SLOTS_PER_EPOCH
            self.summaries[proposer][epoch].blocks_proposed += 1
            self._m_blocks.inc()
            if self.log is not None:
                self.log.info(
                    "block proposed", validator=proposer, slot=int(block.slot)
                )

    def observe_block_attestation_indices(self, att, indices, block_slot: int):
        """Explicit per-attestation accounting when the chain has the
        committee handy (import_block calls this)."""
        epoch = int(att.data.target.epoch)
        delay = block_slot - int(att.data.slot)
        for vi in indices:
            vi = int(vi)
            if not self.watched(vi):
                continue
            s = self.summaries[vi][epoch]
            s.attestations_in_block += 1
            if s.min_inclusion_delay is None or delay < s.min_inclusion_delay:
                s.min_inclusion_delay = delay
            self._m_atts.inc(src="block")

    def observe_sync_committee_message(self, message) -> None:
        vi = int(message.validator_index)
        if not self.watched(vi):
            return
        epoch_guess = int(message.slot)  # stored per-slot under sync key
        self.summaries[vi][epoch_guess].sync_messages_seen += 1

    # --------------------------------------------------------------- reports
    def epoch_report(self, epoch: int) -> dict[int, EpochSummary]:
        out = {}
        for vi, epochs in self.summaries.items():
            if epoch in epochs:
                out[vi] = epochs[epoch]
        return out

    def prune(self, finalized_epoch: int) -> None:
        for vi in list(self.summaries):
            for e in [e for e in self.summaries[vi] if e < finalized_epoch]:
                del self.summaries[vi][e]
