"""ValidatorPubkeyCache — decompressed pubkeys by validator index.

Capability mirror of the reference's
`beacon_node/beacon_chain/src/validator_pubkey_cache.rs:20-24`: the
registry's compressed 48-byte keys are decompressed ONCE at import and
kept indexed by validator index, persisted to the store
(disk-before-memory ordering, :77-120), so signature-set assembly never
pays decompression. In the TPU design this cache is also the source for
the on-HBM pubkey table (SURVEY §7.1 blsrt).
"""

from __future__ import annotations

import struct

from ..common.timeout_lock import TimeoutRwLock
from ..crypto.bls.api import PublicKey

COL_PUBKEY = b"pkc"


class ValidatorPubkeyCache:
    def __init__(self, store=None):
        self.pubkeys: list[PublicKey] = []
        self.indices: dict[bytes, int] = {}
        self.store = store
        # Deadline-bounded RW lock (the reference's
        # VALIDATOR_PUBKEY_CACHE_LOCK_TIMEOUT, batch.rs:63-66): signature
        # batch assembly on processor/HTTP threads takes read, registry
        # imports take write; contention past 1s raises instead of
        # deadlocking.
        self.lock = TimeoutRwLock()
        # Optional HBM mirror (blsrt.DevicePubkeyTable): appended in sync
        # so the device backend can gather by validator index.
        self.device_table = None

    def attach_device_table(self, table, register: bool = True) -> None:
        """Mirror this cache into an HBM table (and optionally register it
        as the process-wide table the JAX backend consults). Uploads the
        current contents immediately."""
        from .. import blsrt

        self.device_table = table
        if len(self.pubkeys) > len(table):
            table.append_pubkeys(self.pubkeys[len(table):])
        if register:
            blsrt.set_device_table(table)

    @classmethod
    def from_state(cls, state, store=None) -> "ValidatorPubkeyCache":
        cache = cls(store)
        cache.import_new_pubkeys(state)
        return cache

    @classmethod
    def load_from_store(cls, store) -> "ValidatorPubkeyCache":
        """(reference: validator_pubkey_cache.rs load_from_store:47-73)"""
        cache = cls(store)
        items = []
        for key, raw in store.iter_column(COL_PUBKEY):
            items.append((struct.unpack(">Q", key)[0], raw))
        items.sort()
        for i, (index, raw) in enumerate(items):
            if index != i:
                raise ValueError("pubkey cache hole in store")
            pk = PublicKey.from_bytes(raw)
            cache.indices[raw] = i
            cache.pubkeys.append(pk)
        return cache

    def import_new_pubkeys(self, state) -> None:
        """Append registry tail; writes the store BEFORE memory so a crash
        leaves a prefix, never a hole (reference: :77-120)."""
        ops = []
        new = []
        for i in range(len(self.pubkeys), len(state.validators)):
            compressed = bytes(state.validators[i].pubkey)
            pk = PublicKey.from_bytes(compressed)  # raises on invalid
            ops.append(("put", COL_PUBKEY, struct.pack(">Q", i), compressed))
            new.append((compressed, pk))
        if self.store is not None and ops:
            self.store.batch(ops)
        with self.lock.write():
            for compressed, pk in new:
                self.indices[compressed] = len(self.pubkeys)
                self.pubkeys.append(pk)
        if self.device_table is not None and new:
            self.device_table.append_pubkeys([pk for _, pk in new])

    @classmethod
    def from_device_table(cls, table, compressed, store=None
                          ) -> "ValidatorPubkeyCache":
        """Registry-scale import: coordinates come from the DEVICE-BUILT
        table (blsrt) and PublicKey objects materialize LAZILY on first
        use — a 1M-validator registry costs zero per-key host
        decompression at startup (the table-resident design; reference
        decompresses every key once at import,
        validator_pubkey_cache.rs:77-120). ``compressed`` is the
        [n, 48] uint8 compressed-key array (blsrt.compressed_pubkeys);
        the compressed->index map builds on first get_index call."""
        cache = cls(store)
        cache.pubkeys = [None] * len(table)
        cache._lazy_table = table
        cache._lazy_compressed = compressed
        cache._indices_built = False
        cache.device_table = table
        return cache

    def _materialize(self, index: int) -> PublicKey:
        from ..ops.points import g1_from_dev

        t = self._lazy_table
        (pt,) = g1_from_dev(
            t._host_x[index:index + 1].astype("int32"),
            t._host_y[index:index + 1].astype("int32"),
            [False],
        )
        pk = PublicKey(pt, bytes(self._lazy_compressed[index].tobytes()))
        self.pubkeys[index] = pk
        return pk

    def get(self, index: int) -> PublicKey | None:
        if 0 <= index < len(self.pubkeys):
            pk = self.pubkeys[index]
            if pk is None and getattr(self, "_lazy_table", None) is not None:
                return self._materialize(index)
            return pk
        return None

    def get_index(self, compressed: bytes) -> int | None:
        # Flag-guarded (NOT dict truthiness: import_new_pubkeys may seed
        # indices with post-genesis keys before the lazy registry build).
        if (getattr(self, "_lazy_compressed", None) is not None
                and not self._indices_built):
            self._indices_built = True
            for i in range(len(self._lazy_compressed)):
                self.indices.setdefault(
                    bytes(self._lazy_compressed[i].tobytes()), i
                )
        return self.indices.get(bytes(compressed))

    def __len__(self) -> int:
        return len(self.pubkeys)

    def as_getter(self):
        """The get_pubkey closure shape signature_sets.py expects."""
        return self.get
