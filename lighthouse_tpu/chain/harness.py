"""BeaconChainHarness — a full in-process chain for tests.

Capability mirror of the reference's
`beacon_node/beacon_chain/src/test_utils.rs:452`: a BeaconChain on
MemoryStore with a ManualSlotClock and deterministic interop keypairs,
able to produce signed blocks (with pooled attestations) and have every
validator attest — the engine behind the reference's 8.5k LoC of chain
integration tests and the simulator.

``backend="fake"`` (default) runs with the always-valid BLS backend and
infinity signatures, isolating consensus logic from crypto cost exactly
like the reference's fake_crypto CI runs; ``backend="python"``/"jax"
produce real signatures.
"""

from __future__ import annotations

from ..common.slot_clock import ManualSlotClock
from ..consensus import helpers as h
from ..consensus.config import ChainSpec, compute_signing_root, minimal_spec
from ..consensus.genesis import interop_genesis_state, interop_keypairs
from ..consensus.types import Checkpoint, spec_types, state_fork_name
from ..crypto.bls import backends as bls_backends
from ..store.hot_cold import HotColdDB, StoreConfig
from ..store.kv import MemoryStore
from .beacon_chain import BeaconChain

INFINITY_SIG = b"\xc0" + bytes(95)


class BeaconChainHarness:
    def __init__(
        self,
        validator_count: int = 16,
        spec: ChainSpec | None = None,
        backend: str = "fake",
        genesis_time: int = 1_600_000_000,
        store=None,
    ):
        self.spec = spec or minimal_spec()
        self.backend = backend
        self.sign = backend != "fake"
        self.keys = interop_keypairs(validator_count)
        self.types = spec_types(self.spec.preset)

        genesis_state = interop_genesis_state(
            self.keys, genesis_time, self.spec, sign_deposits=self.sign
        ) if self.sign else self._fake_genesis(genesis_time)

        self.slot_clock = ManualSlotClock(genesis_time, self.spec.SECONDS_PER_SLOT)
        hot_cold = HotColdDB(
            store if store is not None else MemoryStore(),
            self.spec,
            StoreConfig(slots_per_restore_point=self.spec.preset.SLOTS_PER_EPOCH),
        )
        self.chain = BeaconChain.from_genesis(
            hot_cold, genesis_state, self.spec, self.slot_clock, backend=backend
        )

    def _fake_genesis(self, genesis_time):
        prev = bls_backends._default
        bls_backends.set_default_backend("fake")
        try:
            return interop_genesis_state(
                self.keys, genesis_time, self.spec, sign_deposits=False
            )
        finally:
            bls_backends._default = prev

    # ------------------------------------------------------------------ time
    def advance_slot(self) -> int:
        self.slot_clock.advance_slot()
        self.chain.per_slot_task()
        return self.chain.current_slot()

    def set_slot(self, slot: int) -> None:
        self.slot_clock.set_slot(slot)
        self.chain.per_slot_task()

    # --------------------------------------------------------------- signing
    def sign_block(self, block):
        fork = type(block).fork
        signed_cls = self.types.SIGNED_BLOCK_BY_FORK[fork]
        if not self.sign:
            return signed_cls(message=block, signature=INFINITY_SIG)
        epoch = int(block.slot) // self.spec.preset.SLOTS_PER_EPOCH
        # The domain must use the fork version SCHEDULED for the block's
        # epoch, not the head state's Fork container — at a fork boundary
        # the head is still pre-fork while the block verifies post-fork
        # (the reference VC derives this from the spec's fork schedule).
        domain = self.spec.compute_domain(
            self.spec.DOMAIN_BEACON_PROPOSER,
            self.spec.fork_version_at_epoch(epoch),
            self.chain.genesis_validators_root,
        )
        root = compute_signing_root(block, domain)
        sig = self.keys[int(block.proposer_index)].sign(root)
        return signed_cls(message=block, signature=sig.to_bytes())

    def randao_reveal(self, proposer_index: int, slot: int) -> bytes:
        if not self.sign:
            return INFINITY_SIG
        from ..consensus.ssz import merkleize_chunks, uint64

        epoch = slot // self.spec.preset.SLOTS_PER_EPOCH
        # Scheduled-fork domain (see sign_block): randao for a boundary
        # block verifies under the new fork's version.
        domain = self.spec.compute_domain(
            self.spec.DOMAIN_RANDAO,
            self.spec.fork_version_at_epoch(epoch),
            self.chain.genesis_validators_root,
        )
        root = merkleize_chunks([uint64.hash_tree_root(epoch), domain])
        return self.keys[proposer_index].sign(root).to_bytes()

    # ------------------------------------------------------------ production
    def make_block(self, slot: int | None = None):
        """Produce + sign a block on the current head."""
        slot = slot if slot is not None else self.chain.current_slot()
        state = self.chain.head().state
        adv = state
        if int(state.slot) < slot:
            from ..consensus.transition.advance import partial_state_advance

            adv = partial_state_advance(state.copy(), None, slot, self.spec)
        proposer = h.get_beacon_proposer_index(adv, self.spec)
        block, _post = self.chain.produce_block(
            self.randao_reveal(proposer, slot), slot
        )
        return self.sign_block(block)

    def attest(self, slot: int | None = None, head_root: bytes | None = None):
        """Every scheduled validator attests for ``slot``; attestations are
        verified-for-gossip, applied to fork choice, and fed to the op pool
        (reference: harness attest_to_head + process_attestations)."""
        chain = self.chain
        slot = slot if slot is not None else chain.current_slot()
        p = self.spec.preset
        state = chain.head().state
        if int(state.slot) < slot:
            from ..consensus.transition.advance import partial_state_advance

            state = partial_state_advance(state.copy(), None, slot, self.spec)
        epoch = slot // p.SLOTS_PER_EPOCH
        cache = chain.shuffling_cache.get_or_init(
            state, epoch, chain._shuffling_decision_root(epoch), self.spec
        )
        made = []
        for index, committee in enumerate(cache.committees_at_slot(slot)):
            proto = chain.produce_unaggregated_attestation(slot, index)
            for pos, validator in enumerate(committee):
                att = self.types.Attestation(
                    aggregation_bits=[
                        i == pos for i in range(len(committee))
                    ],
                    data=proto.data,
                    signature=self._attestation_signature(
                        int(validator), proto.data
                    ),
                )
                made.append(att)
        verified = chain.batch_verify_unaggregated_attestations_for_gossip(made)
        out = []
        for v in verified:
            if isinstance(v, Exception):
                raise v
            chain.apply_attestation_to_fork_choice(v)
            chain.add_to_operation_pool(v)
            out.append(v)
        return out

    def _attestation_signature(self, validator_index: int, data) -> bytes:
        if not self.sign:
            return INFINITY_SIG
        # Scheduled-fork domain (see sign_block): target-epoch version
        # from the spec's fork schedule, not the head's Fork container.
        domain = self.spec.compute_domain(
            self.spec.DOMAIN_BEACON_ATTESTER,
            self.spec.fork_version_at_epoch(int(data.target.epoch)),
            self.chain.genesis_validators_root,
        )
        root = compute_signing_root(data, domain)
        return self.keys[validator_index].sign(root).to_bytes()

    # ------------------------------------------------------------- extension
    def extend_chain(self, num_blocks: int, attest: bool = True) -> list[bytes]:
        """Advance one slot per block: import a block, then have all
        validators attest to the new head (reference: extend_chain)."""
        roots = []
        for _ in range(num_blocks):
            slot = self.advance_slot()
            block = self.make_block(slot)
            root = self.chain.process_block(
                block, block_delay_seconds=0.0
            )
            roots.append(root)
            if attest:
                self.attest(slot)
        return roots

    # ---------------------------------------------------------------- status
    def head_slot(self) -> int:
        return int(self.chain.head().block.message.slot)

    def finalized_epoch(self) -> int:
        return self.chain.finalized_checkpoint()[0]

    def justified_epoch(self) -> int:
        return self.chain.fork_choice.store.justified_checkpoint[0]
