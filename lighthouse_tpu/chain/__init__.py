"""Chain core (reference: beacon_node/beacon_chain)."""

from .beacon_chain import (  # noqa: F401
    AttestationError,
    BeaconChain,
    BlockError,
    VerifiedAttestation,
)
from .harness import BeaconChainHarness  # noqa: F401
from .pubkey_cache import ValidatorPubkeyCache  # noqa: F401
