"""Prometheus scrape endpoint (reference: beacon_node/http_metrics +
the VC's equivalent): serves the global registry's text exposition on
`/metrics`, a Chrome-trace dump of recent hot-path spans on `/trace`
(load in chrome://tracing / ui.perfetto.dev), the last serving-loop
SLO summary on `/slo`, plus readiness on `/health`: the governor's
state + per-sentinel detail (common/health.py), HTTP 200 while
healthy/degraded and 503 once critical — a k8s-style readiness probe,
not the old bare liveness."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..common import tracing
from ..common.metrics import REGISTRY


class MetricsServer:
    def __init__(self, registry=None, host: str = "127.0.0.1", port: int = 0):
        reg = registry if registry is not None else REGISTRY

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/metrics":
                    body = reg.gather().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                elif self.path == "/trace":
                    body = json.dumps(
                        {"traceEvents": tracing.chrome_trace()}
                    ).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                elif self.path == "/slo":
                    # most recent serving-loop run's SLO summary
                    # (loadgen/slo.py); {} before any run
                    from ..loadgen import slo

                    body = json.dumps(
                        slo.last_slo_report() or {}
                    ).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                elif self.path == "/health":
                    from ..common import health

                    report = health.health_report()
                    body = json.dumps(report).encode()
                    self.send_response(200 if report["ready"] else 503)
                    self.send_header("Content-Type", "application/json")
                else:
                    body = b"not found"
                    self.send_response(404)
                    self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self._thread: threading.Thread | None = None

    def start(self) -> "MetricsServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
