"""Beacon-API endpoint handlers (reference: beacon_node/http_api/src/lib.rs).

Transport-agnostic: every endpoint is a method taking parsed path/query
arguments and returning JSON-ready dicts; ``server.HttpServer`` mounts
them on real HTTP and ``client.BeaconNodeClient`` can call them
directly in-process (the pattern the reference gets from warp filters +
`common/eth2`'s typed client).

Implemented endpoint families (http_api/src/lib.rs:256-...):
beacon/{genesis, states/*, headers, blocks, pool/*}, node/*, config/*,
validator/{duties/*, blocks, attestation_data, aggregate_attestation,
aggregate_and_proofs, contribution_and_proofs}, events, and the
lighthouse/* introspection extensions.
"""

from __future__ import annotations

from ..chain.beacon_chain import AttestationError, BlockError
from ..consensus import helpers as h
from ..consensus.transition.advance import partial_state_advance
from ..consensus.types import state_fork_name
from .json_codec import container_from_json, container_to_json

VERSION = "lighthouse-tpu/0.1.0"


class ApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message

    def body(self) -> dict:
        return {"code": self.status, "message": self.message}


def _bad(cond: bool, message: str, status: int = 400):
    if not cond:
        raise ApiError(status, message)


class EventBroker:
    """SSE fan-out (reference: http_api/src/events.rs over the chain's
    event handler). Subscribers get (topic, json_payload) tuples via
    ``drain``; queues are bounded (oldest dropped) and at most
    ``MAX_SUBSCRIBERS`` live at once (oldest subscription evicted)."""

    TOPICS = ("head", "block", "attestation", "finalized_checkpoint", "exit")
    MAX_QUEUE = 1024
    MAX_SUBSCRIBERS = 64

    def __init__(self):
        from collections import deque

        self._deque = deque
        self._subs: list[tuple[set, object]] = []

    def subscribe(self, topics):
        queue = self._deque(maxlen=self.MAX_QUEUE)
        self._subs.append((set(topics), queue))
        if len(self._subs) > self.MAX_SUBSCRIBERS:
            self._subs.pop(0)
        return queue

    def drain(self, queue) -> list:
        out = []
        while queue:
            out.append(queue.popleft())
        return out

    def publish(self, topic: str, payload: dict) -> None:
        for topics, queue in self._subs:
            if topic in topics:
                queue.append((topic, payload))


class BeaconApi:
    def __init__(self, chain, network=None):
        self.chain = chain
        self.network = network
        self.events = EventBroker()

    # ----------------------------------------------------------- state access
    def _state_for_id(self, state_id: str):
        chain = self.chain
        if state_id == "head":
            return chain.head().state
        if state_id == "genesis":
            genesis_root = chain.store.genesis_block_root()
            block = chain.store.get_block(genesis_root)
            return chain.store.get_state(bytes(block.message.state_root))
        if state_id == "finalized":
            _, root = chain.finalized_checkpoint()
            state = chain._state_for_block_root(root)
            _bad(state is not None, "finalized state unavailable", 404)
            return state
        if state_id.startswith("0x"):
            state = chain.store.get_state(bytes.fromhex(state_id[2:]))
            _bad(state is not None, "state not found", 404)
            return state
        try:
            slot = int(state_id)
        except ValueError:
            raise ApiError(400, f"invalid state id {state_id!r}")
        head = chain.head()
        if slot == int(head.state.slot):
            return head.state
        for s, root in chain.store.forwards_block_roots_iterator(
            slot, slot, head.state
        ):
            block = chain.store.get_block(root)
            if block is not None and int(block.message.slot) <= slot:
                return chain.store.get_state(bytes(block.message.state_root), slot)
        raise ApiError(404, f"no state at slot {slot}")

    def _block_for_id(self, block_id: str):
        chain = self.chain
        if block_id == "head":
            return chain.head().root, chain.head().block
        if block_id == "genesis":
            root = chain.store.genesis_block_root()
            return root, chain.store.get_block(root)
        if block_id == "finalized":
            _, root = chain.finalized_checkpoint()
            block = chain.store.get_block(root)
            _bad(block is not None, "finalized block unavailable", 404)
            return root, block
        if block_id.startswith("0x"):
            root = bytes.fromhex(block_id[2:])
            block = chain.store.get_block(root)
            _bad(block is not None, "block not found", 404)
            return root, block
        try:
            slot = int(block_id)
        except ValueError:
            raise ApiError(400, f"invalid block id {block_id!r}")
        head = chain.head()
        if slot == int(head.block.message.slot):
            return chain.head().root, head.block
        for s, root in chain.store.forwards_block_roots_iterator(
            slot, slot, head.state
        ):
            block = chain.store.get_block(root)
            if block is not None and int(block.message.slot) == slot:
                return root, block
        raise ApiError(404, f"no canonical block at slot {slot}")

    # --------------------------------------------------------------- /beacon
    def get_genesis(self) -> dict:
        chain = self.chain
        genesis_root = chain.store.genesis_block_root()
        block = chain.store.get_block(genesis_root)
        state = chain.store.get_state(bytes(block.message.state_root))
        return {
            "data": {
                "genesis_time": str(int(state.genesis_time)),
                "genesis_validators_root": "0x"
                + bytes(state.genesis_validators_root).hex(),
                "genesis_fork_version": "0x"
                + chain.spec.GENESIS_FORK_VERSION.hex(),
            }
        }

    def get_state_root(self, state_id: str) -> dict:
        state = self._state_for_id(state_id)
        return {"data": {"root": "0x" + state.hash_tree_root().hex()}}

    def get_state_fork(self, state_id: str) -> dict:
        state = self._state_for_id(state_id)
        return {"data": container_to_json(state.fork)}

    def get_finality_checkpoints(self, state_id: str) -> dict:
        state = self._state_for_id(state_id)
        return {
            "data": {
                "previous_justified": container_to_json(
                    state.previous_justified_checkpoint
                ),
                "current_justified": container_to_json(
                    state.current_justified_checkpoint
                ),
                "finalized": container_to_json(state.finalized_checkpoint),
            }
        }

    def get_validators(self, state_id: str, indices=None, statuses=None) -> dict:
        state = self._state_for_id(state_id)
        spec = self.chain.spec
        epoch = h.get_current_epoch(state, spec)
        out = []
        for i, v in enumerate(state.validators):
            if indices is not None and i not in indices:
                continue
            status = _validator_status(v, epoch, spec)
            if statuses is not None and status not in statuses:
                continue
            out.append(
                {
                    "index": str(i),
                    "balance": str(int(state.balances[i])),
                    "status": status,
                    "validator": container_to_json(v),
                }
            )
        return {"data": out}

    def get_validator(self, state_id: str, validator_id: str) -> dict:
        state = self._state_for_id(state_id)
        index = self._validator_index(state, validator_id)
        _bad(index is not None, "validator not found", 404)
        spec = self.chain.spec
        v = state.validators[index]
        return {
            "data": {
                "index": str(index),
                "balance": str(int(state.balances[index])),
                "status": _validator_status(
                    v, h.get_current_epoch(state, spec), spec
                ),
                "validator": container_to_json(v),
            }
        }

    def _validator_index(self, state, validator_id: str):
        if validator_id.startswith("0x"):
            pk = bytes.fromhex(validator_id[2:])
            for i, v in enumerate(state.validators):
                if bytes(v.pubkey) == pk:
                    return i
            return None
        try:
            i = int(validator_id)
        except ValueError:
            raise ApiError(400, f"invalid validator id {validator_id!r}")
        return i if 0 <= i < len(state.validators) else None

    def get_validator_balances(self, state_id: str, indices=None) -> dict:
        state = self._state_for_id(state_id)
        return {
            "data": [
                {"index": str(i), "balance": str(int(b))}
                for i, b in enumerate(state.balances)
                if indices is None or i in indices
            ]
        }

    def get_committees(self, state_id: str, epoch=None, index=None, slot=None) -> dict:
        state = self._state_for_id(state_id)
        spec = self.chain.spec
        p = spec.preset
        epoch = int(epoch) if epoch is not None else h.get_current_epoch(state, spec)
        out = []
        for s in range(epoch * p.SLOTS_PER_EPOCH, (epoch + 1) * p.SLOTS_PER_EPOCH):
            if slot is not None and s != int(slot):
                continue
            count = h.get_committee_count_per_slot(state, epoch, spec)
            for ci in range(count):
                if index is not None and ci != int(index):
                    continue
                committee = h.get_beacon_committee(state, s, ci, spec)
                out.append(
                    {
                        "index": str(ci),
                        "slot": str(s),
                        "validators": [str(int(v)) for v in committee],
                    }
                )
        return {"data": out}

    def get_header(self, block_id: str) -> dict:
        root, block = self._block_for_id(block_id)
        return {"data": self._header_entry(root, block)}

    def get_headers(self, slot=None, parent_root=None) -> dict:
        if slot is not None:
            root, block = self._block_for_id(str(int(slot)))
            return {"data": [self._header_entry(root, block)]}
        head = self.chain.head()
        return {"data": [self._header_entry(head.root, head.block)]}

    def _header_entry(self, root: bytes, signed_block) -> dict:
        msg = signed_block.message
        return {
            "root": "0x" + root.hex(),
            "canonical": True,
            "header": {
                "message": {
                    "slot": str(int(msg.slot)),
                    "proposer_index": str(int(msg.proposer_index)),
                    "parent_root": "0x" + bytes(msg.parent_root).hex(),
                    "state_root": "0x" + bytes(msg.state_root).hex(),
                    "body_root": "0x" + msg.body.hash_tree_root().hex(),
                },
                "signature": "0x" + bytes(signed_block.signature).hex(),
            },
        }

    def get_block(self, block_id: str) -> dict:
        root, block = self._block_for_id(block_id)
        return {
            "version": type(block.message).fork,
            "data": container_to_json(block),
        }

    def get_block_root(self, block_id: str) -> dict:
        root, _ = self._block_for_id(block_id)
        return {"data": {"root": "0x" + root.hex()}}

    def get_block_attestations(self, block_id: str) -> dict:
        _, block = self._block_for_id(block_id)
        return {
            "data": [
                container_to_json(a) for a in block.message.body.attestations
            ]
        }

    def publish_block(self, block_json_or_obj) -> dict:
        chain = self.chain
        if isinstance(block_json_or_obj, dict):
            fork = chain.spec.fork_name_at_epoch(
                int(block_json_or_obj["message"]["slot"])
                // chain.spec.preset.SLOTS_PER_EPOCH
            )
            block = container_from_json(
                chain.types.SIGNED_BLOCK_BY_FORK[fork], block_json_or_obj
            )
        else:
            block = block_json_or_obj
        # gossip first, then import (http_api publish semantics)
        if self.network is not None:
            self.network.publish_block(block)
        try:
            root = chain.process_block(block)
        except BlockError as e:
            raise ApiError(400, f"block rejected: {e}")
        self.events.publish("block", {
            "slot": str(int(block.message.slot)),
            "block": "0x" + root.hex(),
        })
        self.events.publish("head", {
            "slot": str(int(block.message.slot)),
            "block": "0x" + chain.head().root.hex(),
            "state": "0x" + bytes(block.message.state_root).hex(),
        })
        return {}

    # ------------------------------------------------------------ /pool
    def pool_attestations(self, att_json_list) -> dict:
        chain = self.chain
        failures = []
        for i, data in enumerate(att_json_list):
            att = (
                container_from_json(chain.types.Attestation, data)
                if isinstance(data, dict)
                else data
            )
            try:
                verified = chain.verify_unaggregated_attestation_for_gossip(att)
            except AttestationError as e:
                failures.append({"index": i, "message": str(e)})
                continue
            chain.apply_attestation_to_fork_choice(verified)
            chain.add_to_naive_aggregation_pool(verified)
            if self.network is not None:
                self.network.publish_attestation(att)
            self.events.publish(
                "attestation", container_to_json(att)
            )
        if failures:
            raise ApiError(400, f"some attestations failed: {failures}")
        return {}

    def get_pool_attestations(self) -> dict:
        return {
            "data": [
                container_to_json(a)
                for a in self.chain.op_pool.all_attestations()
            ]
        }

    def pool_voluntary_exit(self, exit_json_or_obj) -> dict:
        from ..consensus.types import SignedVoluntaryExit
        from ..consensus.verify_operation import OperationError, verify_exit

        chain = self.chain
        signed = (
            container_from_json(SignedVoluntaryExit, exit_json_or_obj)
            if isinstance(exit_json_or_obj, dict)
            else exit_json_or_obj
        )
        try:
            op = verify_exit(
                chain.head().state, signed, chain.spec, backend=chain.backend
            )
        except OperationError as e:
            raise ApiError(400, f"exit rejected: {e}")
        chain.op_pool.insert_voluntary_exit(op)
        if self.network is not None:
            self.network.publish_voluntary_exit(signed)
        self.events.publish("exit", container_to_json(signed))
        return {}

    # ------------------------------------------------------ sync committees
    def pool_sync_committees(self, messages_json) -> dict:
        """POST /eth/v1/beacon/pool/sync_committees."""
        chain = self.chain
        failures = []
        for i, data in enumerate(messages_json):
            msg = (
                container_from_json(chain.types.SyncCommitteeMessage, data)
                if isinstance(data, dict)
                else data
            )
            try:
                chain.verify_sync_committee_message_for_gossip(msg)
            except AttestationError as e:
                failures.append({"index": i, "message": str(e)})
                continue
            chain.add_to_naive_sync_pool(msg)
            if self.network is not None:
                # route to the member's actual subnet topic(s)
                for subnet in chain.sync_subnets_for_validator(
                    int(msg.validator_index)
                ):
                    self.network._publish_kind(f"sync_committee_{subnet}", msg)
        if failures:
            raise ApiError(400, f"some sync messages failed: {failures}")
        return {}

    def sync_committee_contribution(self, slot: int, subcommittee_index: int,
                                    beacon_block_root: str) -> dict:
        root = bytes.fromhex(beacon_block_root.removeprefix("0x"))
        contribution = self.chain.produce_sync_contribution(
            int(slot), root, int(subcommittee_index)
        )
        _bad(contribution is not None, "no contribution available", 404)
        return {"data": container_to_json(contribution)}

    def publish_contribution_and_proofs(self, contributions_json) -> dict:
        chain = self.chain
        failures = []
        for i, data in enumerate(contributions_json):
            signed = (
                container_from_json(chain.types.SignedContributionAndProof, data)
                if isinstance(data, dict)
                else data
            )
            try:
                chain.verify_sync_contribution_for_gossip(signed)
            except AttestationError as e:
                failures.append({"index": i, "message": str(e)})
                continue
            if self.network is not None:
                from ..network import gossip as g

                self.network._publish_kind(g.SYNC_CONTRIBUTION_AND_PROOF, signed)
        if failures:
            raise ApiError(400, f"some contributions failed: {failures}")
        return {}

    def duties_sync(self, epoch: int, indices) -> dict:
        """POST /eth/v1/validator/duties/sync/{epoch} — membership of the
        sync committee for our validators (duties_service/sync.rs)."""
        state = self._duties_state(int(epoch))
        if state_fork_name(state) == "phase0":
            return {"data": []}
        want = {int(i) for i in indices}
        members = h.current_sync_committee_indices(state, self.chain.spec)
        duties = []
        for vi in sorted(want):
            positions = [p for p, m in enumerate(members) if m == vi]
            if positions:
                duties.append(
                    {
                        "pubkey": "0x" + bytes(state.validators[vi].pubkey).hex(),
                        "validator_index": str(vi),
                        "validator_sync_committee_indices": [
                            str(p) for p in positions
                        ],
                    }
                )
        return {"data": duties}

    # ----------------------------------------------------------------- /debug
    def get_debug_state(self, state_id: str) -> dict:
        """Full BeaconState JSON (eth/v2/debug/beacon/states — the
        checkpoint-sync download, builder.rs:252-365 consumer side)."""
        state = self._state_for_id(state_id)
        return {
            "version": state_fork_name(state),
            "data": container_to_json(state),
        }

    # ------------------------------------------------------------------ /node
    def node_version(self) -> dict:
        return {"data": {"version": VERSION}}

    def node_health(self) -> int:
        return 200

    def node_syncing(self) -> dict:
        head_slot = int(self.chain.head().block.message.slot)
        current = self.chain.current_slot()
        distance = max(0, current - head_slot)
        return {
            "data": {
                "head_slot": str(head_slot),
                "sync_distance": str(distance),
                "is_syncing": distance > 1,
                "is_optimistic": False,
            }
        }

    def node_identity(self) -> dict:
        node_id = self.network.node_id if self.network else "solo"
        return {
            "data": {
                "peer_id": node_id,
                "enr": "",
                "p2p_addresses": [],
                "discovery_addresses": [],
                "metadata": {"seq_number": "0", "attnets": "0x", "syncnets": "0x"},
            }
        }

    def node_peers(self) -> dict:
        if self.network is None:
            return {"data": [], "meta": {"count": 0}}
        peers = self.network.peer_manager.connected_peers()
        return {
            "data": [
                {
                    "peer_id": p,
                    "state": "connected",
                    "direction": "outbound",
                    "last_seen_p2p_address": "",
                }
                for p in peers
            ],
            "meta": {"count": len(peers)},
        }

    # ---------------------------------------------------------------- /config
    def config_spec(self) -> dict:
        spec = self.chain.spec
        p = spec.preset
        out = {}
        for name in (
            "SECONDS_PER_SLOT",
            "MIN_GENESIS_ACTIVE_VALIDATOR_COUNT",
            "ETH1_FOLLOW_DISTANCE",
            "GENESIS_DELAY",
            "CHURN_LIMIT_QUOTIENT",
            "MIN_PER_EPOCH_CHURN_LIMIT",
        ):
            if hasattr(spec, name):
                out[name] = str(getattr(spec, name))
        for name in (
            "SLOTS_PER_EPOCH",
            "TARGET_COMMITTEE_SIZE",
            "MAX_COMMITTEES_PER_SLOT",
            "SHARD_COMMITTEE_PERIOD",
            "SYNC_COMMITTEE_SIZE",
        ):
            out[name] = str(getattr(p, name))
        out["PRESET_BASE"] = p.name
        out["GENESIS_FORK_VERSION"] = "0x" + spec.GENESIS_FORK_VERSION.hex()
        return {"data": out}

    def config_fork_schedule(self) -> dict:
        spec = self.chain.spec
        forks = [
            {
                "previous_version": "0x" + spec.GENESIS_FORK_VERSION.hex(),
                "current_version": "0x" + spec.GENESIS_FORK_VERSION.hex(),
                "epoch": "0",
            }
        ]
        if spec.ALTAIR_FORK_EPOCH is not None:
            forks.append(
                {
                    "previous_version": "0x" + spec.GENESIS_FORK_VERSION.hex(),
                    "current_version": "0x" + spec.ALTAIR_FORK_VERSION.hex(),
                    "epoch": str(spec.ALTAIR_FORK_EPOCH),
                }
            )
        if spec.BELLATRIX_FORK_EPOCH is not None:
            forks.append(
                {
                    "previous_version": "0x" + spec.ALTAIR_FORK_VERSION.hex(),
                    "current_version": "0x" + spec.BELLATRIX_FORK_VERSION.hex(),
                    "epoch": str(spec.BELLATRIX_FORK_EPOCH),
                }
            )
        return {"data": forks}

    def config_deposit_contract(self) -> dict:
        spec = self.chain.spec
        address = getattr(spec, "DEPOSIT_CONTRACT_ADDRESS", b"\x00" * 20)
        return {
            "data": {
                "chain_id": str(getattr(spec, "DEPOSIT_CHAIN_ID", 1)),
                "address": "0x" + bytes(address).hex(),
            }
        }

    # ------------------------------------------------------------- /validator
    def _duties_state(self, epoch: int):
        """State inside ``epoch``, bounded to [0, current_epoch + 1]:
        the reference serves duties only for current/next epoch (future
        RANDAO is undetermined; unbounded advance is a DoS vector)."""
        chain = self.chain
        p = chain.spec.preset
        current_epoch = max(
            chain.current_slot(), int(chain.head().state.slot)
        ) // p.SLOTS_PER_EPOCH
        _bad(0 <= epoch <= current_epoch + 1,
             f"duties epoch {epoch} outside [0, {current_epoch + 1}]")
        state = chain.head().state
        target_slot = epoch * p.SLOTS_PER_EPOCH
        if int(state.slot) < target_slot:
            return partial_state_advance(
                state.copy(), None, target_slot, chain.spec
            )
        if int(state.slot) // p.SLOTS_PER_EPOCH > epoch:
            # past epoch: replay a canonical state, then make sure it
            # actually reaches the epoch (a skipped epoch-start slot
            # leaves the stored state one epoch back)
            state = self._state_for_id(str(target_slot))
            if int(state.slot) < target_slot:
                state = partial_state_advance(
                    state.copy(), None, target_slot, chain.spec
                )
        return state

    def duties_proposer(self, epoch: int) -> dict:
        chain = self.chain
        p = chain.spec.preset
        epoch = int(epoch)
        target_slot = epoch * p.SLOTS_PER_EPOCH
        state = self._duties_state(epoch)
        duties = []
        for slot in range(target_slot, target_slot + p.SLOTS_PER_EPOCH):
            index = h.get_beacon_proposer_index_at_slot(state, slot, chain.spec)
            duties.append(
                {
                    "pubkey": "0x" + bytes(state.validators[index].pubkey).hex(),
                    "validator_index": str(index),
                    "slot": str(slot),
                }
            )
        return {
            "dependent_root": "0x" + self._proposer_dependent_root(epoch).hex(),
            "data": duties,
        }

    def _proposer_dependent_root(self, epoch: int) -> bytes:
        p = self.chain.spec.preset
        decision_slot = epoch * p.SLOTS_PER_EPOCH - 1
        if decision_slot < 0:
            return self.chain.genesis_block_root
        root = self.chain.fork_choice.proto.ancestor_at_slot(
            self.chain.head().root, decision_slot
        )
        return root if root is not None else self.chain.genesis_block_root

    def duties_attester(self, epoch: int, indices) -> dict:
        chain = self.chain
        p = chain.spec.preset
        epoch = int(epoch)
        target_slot = epoch * p.SLOTS_PER_EPOCH
        state = self._duties_state(epoch)
        want = {int(i) for i in indices}
        duties = []
        for slot in range(target_slot, target_slot + p.SLOTS_PER_EPOCH):
            count = h.get_committee_count_per_slot(state, epoch, chain.spec)
            for ci in range(count):
                committee = h.get_beacon_committee(state, slot, ci, chain.spec)
                for pos, vi in enumerate(committee):
                    if int(vi) in want:
                        duties.append(
                            {
                                "pubkey": "0x"
                                + bytes(state.validators[int(vi)].pubkey).hex(),
                                "validator_index": str(int(vi)),
                                "committee_index": str(ci),
                                "committee_length": str(len(committee)),
                                "committees_at_slot": str(count),
                                "validator_committee_index": str(pos),
                                "slot": str(slot),
                            }
                        )
        decision_root = chain._shuffling_decision_root(epoch)
        return {"dependent_root": "0x" + decision_root.hex(), "data": duties}

    def produce_block(self, slot: int, randao_reveal: str, graffiti=None) -> dict:
        chain = self.chain
        reveal = (
            bytes.fromhex(randao_reveal.removeprefix("0x"))
            if isinstance(randao_reveal, str)
            else randao_reveal
        )
        graffiti_bytes = (
            bytes.fromhex(graffiti.removeprefix("0x")) if graffiti else b""
        )
        block, _ = chain.produce_block(reveal, int(slot), graffiti_bytes)
        return {
            "version": type(block).fork,
            "data": container_to_json(block),
        }

    def attestation_data(self, slot: int, committee_index: int) -> dict:
        att = self.chain.produce_unaggregated_attestation(
            int(slot), int(committee_index)
        )
        return {"data": container_to_json(att.data)}

    def aggregate_attestation(self, slot: int, attestation_data_root: str) -> dict:
        root = bytes.fromhex(attestation_data_root.removeprefix("0x"))
        entry = self.chain.naive_aggregation_pool.get_by_root(root)
        _bad(entry is not None, "no aggregate for data root", 404)
        data, bits, sig = entry
        att = self.chain.types.Attestation(
            aggregation_bits=bits, data=data, signature=sig.to_bytes()
        )
        return {"data": container_to_json(att)}

    def publish_aggregate_and_proofs(self, aggregates) -> dict:
        chain = self.chain
        failures = []
        for i, data in enumerate(aggregates):
            agg = (
                container_from_json(chain.types.SignedAggregateAndProof, data)
                if isinstance(data, dict)
                else data
            )
            try:
                verified = chain.verify_aggregated_attestation_for_gossip(agg)
            except AttestationError as e:
                failures.append({"index": i, "message": str(e)})
                continue
            chain.apply_attestation_to_fork_choice(verified)
            chain.add_to_operation_pool(verified)
            if self.network is not None:
                self.network.publish_aggregate(agg)
        if failures:
            raise ApiError(400, f"some aggregates failed: {failures}")
        return {}

    def subscribe_beacon_committee(self, subscriptions) -> dict:
        """POST validator/beacon_committee_subscriptions → the attestation
        subnet service (http_api post_validator_beacon_committee_
        subscriptions → AttestationService.validator_subscriptions)."""
        if self.network is not None:
            from ..network.subnet_service import ValidatorSubscription

            try:
                parsed = [
                    ValidatorSubscription(
                        validator_index=int(s["validator_index"]),
                        committee_index=int(s["committee_index"]),
                        slot=int(s["slot"]),
                        committee_count_at_slot=int(s["committees_at_slot"]),
                        is_aggregator=bool(s.get("is_aggregator", False)),
                    )
                    for s in subscriptions
                ]
            except (KeyError, TypeError, ValueError) as e:
                raise ApiError(400, f"malformed subscription: {e}")
            self.network.process_attester_subscriptions(parsed)
        return {}

    def prepare_beacon_proposer(self, preparations) -> dict:
        """POST validator/prepare_beacon_proposer: per-proposer fee
        recipients for payload attributes (http_api
        post_validator_prepare_beacon_proposer -> execution layer
        proposer preparation). Malformed entries are a 400 — a bad
        address stored here would surface as a failed proposal when the
        engine rejects the payload attributes."""
        validated = []
        for p in preparations or []:
            try:
                index = int(p["validator_index"])
                recipient = str(p["fee_recipient"])
                raw = bytes.fromhex(recipient.removeprefix("0x"))
            except (KeyError, TypeError, ValueError) as e:
                raise ApiError(400, f"malformed preparation: {e}")
            if index < 0 or len(raw) != 20:
                raise ApiError(400, f"invalid preparation for index {index}")
            validated.append((index, "0x" + raw.hex()))
        for index, recipient in validated:
            self.chain.proposer_preparations[index] = recipient
        return {}

    def subscribe_sync_committee(self, subscriptions) -> dict:
        """POST validator/sync_committee_subscriptions → sync subnet
        service (sync_subnets.rs path)."""
        if self.network is not None:
            from ..network.subnet_service import SyncCommitteeSubscription

            try:
                parsed = [
                    SyncCommitteeSubscription(
                        validator_index=int(s["validator_index"]),
                        sync_committee_indices=tuple(
                            int(i) for i in s["sync_committee_indices"]
                        ),
                        until_epoch=int(s["until_epoch"]),
                    )
                    for s in subscriptions
                ]
            except (KeyError, TypeError, ValueError) as e:
                raise ApiError(400, f"malformed subscription: {e}")
            self.network.process_sync_subscriptions(parsed)
        return {}

    def pool_proposer_slashings(self, slashing_json_or_obj) -> dict:
        """POST beacon/pool/proposer_slashings (gossip-verify + pool +
        publish, http_api pool handlers)."""
        from ..consensus.types import ProposerSlashing
        from ..consensus.verify_operation import (
            OperationError,
            verify_proposer_slashing,
        )

        chain = self.chain
        slashing = (
            container_from_json(ProposerSlashing, slashing_json_or_obj)
            if isinstance(slashing_json_or_obj, dict)
            else slashing_json_or_obj
        )
        try:
            op = verify_proposer_slashing(
                chain.head().state, slashing, chain.spec, backend=chain.backend
            )
        except OperationError as e:
            raise ApiError(400, f"proposer slashing rejected: {e}")
        chain.op_pool.insert_proposer_slashing(op)
        if self.network is not None:
            self.network.publish_proposer_slashing(slashing)
        return {}

    def pool_attester_slashings(self, slashing_json_or_obj) -> dict:
        from ..consensus.verify_operation import (
            OperationError,
            verify_attester_slashing,
        )

        chain = self.chain
        slashing = (
            container_from_json(
                self.chain.types.AttesterSlashing, slashing_json_or_obj
            )
            if isinstance(slashing_json_or_obj, dict)
            else slashing_json_or_obj
        )
        try:
            op = verify_attester_slashing(
                chain.head().state, slashing, chain.spec, backend=chain.backend
            )
        except OperationError as e:
            raise ApiError(400, f"attester slashing rejected: {e}")
        chain.op_pool.insert_attester_slashing(op)
        if self.network is not None:
            self.network.publish_attester_slashing(slashing)
        return {}

    # ------------------------------------------------------------ /lighthouse
    def lighthouse_syncing_state(self) -> dict:
        if self.network is None:
            return {"data": "Synced"}
        return {"data": self.network.sync.state.value}

    def lighthouse_database_info(self) -> dict:
        """GET /lighthouse/database (http_api/src/database.rs)."""
        from ..store.hot_cold import COL_BLOCK, COL_STATE, COL_SUMMARY
        from ..store.schema_change import read_schema_version

        store = self.chain.store

        def count_keys(column: bytes) -> int:
            # key-only when the engine offers it; values untouched
            iter_keys = getattr(store.db, "iter_keys", None)
            if iter_keys is not None:
                return sum(1 for _ in iter_keys(column))
            return sum(1 for _ in store.db.iter_column(column))

        counts = {
            "blocks": count_keys(COL_BLOCK),
            "hot_states": count_keys(COL_STATE),
            "summaries": count_keys(COL_SUMMARY),
        }
        return {
            "data": {
                "schema_version": read_schema_version(store.db),
                "split_slot": str(store.split.slot),
                "slots_per_restore_point": str(
                    store.config.slots_per_restore_point
                ),
                "counts": counts,
            }
        }

    def lighthouse_block_rewards(self, start_slot: int, end_slot: int) -> dict:
        """GET /lighthouse/analysis/block_rewards
        (http_api/src/block_rewards.rs, condensed): per-block counts of
        included operations (the reward drivers)."""
        start, end = int(start_slot), int(end_slot)
        _bad(start <= end, "inverted slot range")
        _bad(end - start <= 256, "slot range too large")
        head = self.chain.head()
        head_slot = int(head.block.message.slot)
        try:
            pairs = [
                (slot, root)
                for slot, root in self.chain.store.forwards_block_roots_iterator(
                    start, min(end, head_slot), head.state
                )
            ]
        except Exception as e:
            # e.g. a slot above the split but outside the head state's
            # root window (stalled finality): a client error, not a 500
            raise ApiError(400, f"slot range unavailable: {e}")
        # the iterator covers roots recorded BEHIND the head state; the
        # head block itself is appended explicitly
        if start <= head_slot <= end:
            pairs.append((head_slot, head.root))
        out = []
        for slot, root in pairs:
            block = self.chain.store.get_block(root)
            if block is None or int(block.message.slot) != slot:
                continue
            body = block.message.body
            out.append(
                {
                    "block_root": "0x" + root.hex(),
                    "slot": str(slot),
                    "attestations": len(body.attestations),
                    "proposer_slashings": len(body.proposer_slashings),
                    "attester_slashings": len(body.attester_slashings),
                    "sync_participation": (
                        sum(body.sync_aggregate.sync_committee_bits)
                        if hasattr(body, "sync_aggregate")
                        else 0
                    ),
                }
            )
        return {"data": out}

    def lighthouse_attestation_performance(self, validator_index: int,
                                           start_epoch: int,
                                           end_epoch: int) -> dict:
        """GET /lighthouse/analysis/attestation_performance
        (attestation_performance.rs, backed by the validator monitor)."""
        vi = int(validator_index)
        start_epoch, end_epoch = int(start_epoch), int(end_epoch)
        _bad(start_epoch <= end_epoch, "inverted epoch range")
        _bad(end_epoch - start_epoch <= 256, "epoch range too large")
        monitor = self.chain.validator_monitor
        out = []
        for epoch in range(start_epoch, end_epoch + 1):
            summary = monitor.summaries.get(vi, {}).get(epoch)
            out.append(
                {
                    "epoch": str(epoch),
                    "attestations_seen": summary.attestations_seen if summary else 0,
                    "attestations_in_block": (
                        summary.attestations_in_block if summary else 0
                    ),
                    "min_inclusion_delay": (
                        summary.min_inclusion_delay if summary else None
                    ),
                }
            )
        return {"data": {"validator_index": str(vi), "epochs": out}}

    def lighthouse_block_packing_efficiency(self, start_slot: int,
                                            end_slot: int) -> dict:
        """GET /lighthouse/analysis/block_packing_efficiency: included
        attestations vs the per-block ceiling."""
        p = self.chain.spec.preset
        rewards = self.lighthouse_block_rewards(start_slot, end_slot)["data"]
        out = []
        for r in rewards:
            out.append(
                {
                    "block_root": r["block_root"],
                    "slot": r["slot"],
                    "included_attestations": r["attestations"],
                    "max_attestations": p.MAX_ATTESTATIONS,
                    "efficiency": round(
                        r["attestations"] / max(1, p.MAX_ATTESTATIONS), 4
                    ),
                }
            )
        return {"data": out}

    def lighthouse_proto_array(self) -> dict:
        proto = self.chain.fork_choice.proto.proto_array
        return {
            "data": {
                "nodes": [
                    {
                        "slot": str(n.slot),
                        "root": "0x" + n.root.hex(),
                        "parent": n.parent,
                        "weight": str(n.weight),
                    }
                    for n in proto.nodes
                ]
            }
        }


def _validator_status(v, epoch: int, spec) -> str:
    """Condensed eth2 validator status taxonomy."""
    from ..consensus.config import FAR_FUTURE_EPOCH

    if int(v.activation_epoch) > epoch:
        return (
            "pending_queued"
            if int(v.activation_eligibility_epoch) <= epoch
            else "pending_initialized"
        )
    if int(v.exit_epoch) == FAR_FUTURE_EPOCH:
        return "active_slashed" if v.slashed else "active_ongoing"
    if epoch < int(v.exit_epoch):
        return "active_exiting"
    if epoch < int(v.withdrawable_epoch):
        return "exited_slashed" if v.slashed else "exited_unslashed"
    return "withdrawal_possible"
