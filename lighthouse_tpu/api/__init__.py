"""Beacon-API layer (reference: beacon_node/http_api, 8.9k LoC warp +
common/eth2 typed client, 4.2k LoC).

* ``json_codec``  — eth2-API JSON conventions (ints as decimal strings,
  bytes as 0x-hex, bitfields as SSZ-hex) derived from SSZ schemas.
* ``beacon_api``  — transport-agnostic endpoint handlers over a
  BeaconChain + NetworkService (http_api/src/lib.rs:256 filter tree).
* ``server``      — stdlib threading HTTP server adapter + SSE events.
* ``client``      — BeaconNodeClient (common/eth2/src/lib.rs:134):
  typed access over real HTTP or direct in-process dispatch.
"""

from .beacon_api import ApiError, BeaconApi
from .client import BeaconNodeClient
from .json_codec import container_from_json, container_to_json
from .server import HttpServer

__all__ = [
    "ApiError",
    "BeaconApi",
    "BeaconNodeClient",
    "HttpServer",
    "container_from_json",
    "container_to_json",
]
