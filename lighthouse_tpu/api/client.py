"""BeaconNodeClient — the typed Beacon-API client.

Capability mirror of `common/eth2/src/lib.rs:134` (BeaconNodeHttpClient):
every endpoint the validator client / checkpoint sync / simulator needs,
as typed methods. Two transports:

* ``BeaconNodeClient(url=...)``  — real HTTP via urllib (the production
  path against ``server.HttpServer`` or any Beacon-API node);
* ``BeaconNodeClient(api=...)``  — direct in-process dispatch onto a
  ``BeaconApi`` (the reference's pattern of handing the harness's
  client to services in tests, without sockets).

Raises ``ApiError`` on non-2xx, mirroring eth2::Error::StatusCode.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

from .beacon_api import ApiError, BeaconApi


class BeaconNodeClient:
    def __init__(self, url: str | None = None, api: BeaconApi | None = None,
                 timeout: float = 10.0):
        if (url is None) == (api is None):
            raise ValueError("exactly one of url/api required")
        self.url = url.rstrip("/") if url else None
        self.api = api
        self.timeout = timeout

    # ------------------------------------------------------------- transport
    def _http(self, method: str, path: str, body=None):
        req = urllib.request.Request(
            self.url + path,
            method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                raw = resp.read()
                return json.loads(raw) if raw else {}
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read())
                message = detail.get("message", str(e))
            except Exception:
                message = str(e)
            raise ApiError(e.code, message) from None

    def _get(self, path: str, direct, *args, **kwargs):
        if self.api is not None:
            return direct(*args, **kwargs)
        return self._http("GET", path)

    def _post(self, path: str, direct, *args, body=None, **kwargs):
        if self.api is not None:
            return direct(*args, **kwargs)
        return self._http("POST", path, body=body)

    # --------------------------------------------------------------- beacon
    def get_genesis(self):
        return self._get("/eth/v1/beacon/genesis", lambda: self.api.get_genesis())

    def get_state_fork(self, state_id="head"):
        return self._get(
            f"/eth/v1/beacon/states/{state_id}/fork",
            lambda: self.api.get_state_fork(state_id),
        )

    def get_finality_checkpoints(self, state_id="head"):
        return self._get(
            f"/eth/v1/beacon/states/{state_id}/finality_checkpoints",
            lambda: self.api.get_finality_checkpoints(state_id),
        )

    def get_validators(self, state_id="head"):
        return self._get(
            f"/eth/v1/beacon/states/{state_id}/validators",
            lambda: self.api.get_validators(state_id),
        )

    def get_validator(self, validator_id, state_id="head"):
        return self._get(
            f"/eth/v1/beacon/states/{state_id}/validators/{validator_id}",
            lambda: self.api.get_validator(state_id, str(validator_id)),
        )

    def get_header(self, block_id="head"):
        return self._get(
            f"/eth/v1/beacon/headers/{block_id}",
            lambda: self.api.get_header(block_id),
        )

    def get_block(self, block_id="head"):
        return self._get(
            f"/eth/v2/beacon/blocks/{block_id}",
            lambda: self.api.get_block(block_id),
        )

    def get_block_root(self, block_id="head"):
        return self._get(
            f"/eth/v1/beacon/blocks/{block_id}/root",
            lambda: self.api.get_block_root(block_id),
        )

    def publish_block(self, block_json):
        return self._post(
            "/eth/v1/beacon/blocks",
            lambda: self.api.publish_block(block_json),
            body=block_json,
        )

    def post_pool_attestations(self, atts_json):
        return self._post(
            "/eth/v1/beacon/pool/attestations",
            lambda: self.api.pool_attestations(atts_json),
            body=atts_json,
        )

    def post_voluntary_exit(self, exit_json):
        return self._post(
            "/eth/v1/beacon/pool/voluntary_exits",
            lambda: self.api.pool_voluntary_exit(exit_json),
            body=exit_json,
        )

    def post_proposer_slashing(self, slashing_json):
        return self._post(
            "/eth/v1/beacon/pool/proposer_slashings",
            lambda: self.api.pool_proposer_slashings(slashing_json),
            body=slashing_json,
        )

    def post_attester_slashing(self, slashing_json):
        return self._post(
            "/eth/v1/beacon/pool/attester_slashings",
            lambda: self.api.pool_attester_slashings(slashing_json),
            body=slashing_json,
        )

    def post_beacon_committee_subscriptions(self, subscriptions_json):
        return self._post(
            "/eth/v1/validator/beacon_committee_subscriptions",
            lambda: self.api.subscribe_beacon_committee(subscriptions_json),
            body=subscriptions_json,
        )

    def post_prepare_beacon_proposer(self, preparations_json):
        return self._post(
            "/eth/v1/validator/prepare_beacon_proposer",
            lambda: self.api.prepare_beacon_proposer(preparations_json),
            body=preparations_json,
        )

    def post_sync_committee_subscriptions(self, subscriptions_json):
        return self._post(
            "/eth/v1/validator/sync_committee_subscriptions",
            lambda: self.api.subscribe_sync_committee(subscriptions_json),
            body=subscriptions_json,
        )

    def get_debug_state(self, state_id="head"):
        return self._get(
            f"/eth/v2/debug/beacon/states/{state_id}",
            lambda: self.api.get_debug_state(state_id),
        )

    # ----------------------------------------------------------------- node
    def node_version(self):
        return self._get("/eth/v1/node/version", lambda: self.api.node_version())

    def node_syncing(self):
        return self._get("/eth/v1/node/syncing", lambda: self.api.node_syncing())

    def config_spec(self):
        return self._get("/eth/v1/config/spec", lambda: self.api.config_spec())

    # ------------------------------------------------------------- validator
    def get_proposer_duties(self, epoch: int):
        return self._get(
            f"/eth/v1/validator/duties/proposer/{int(epoch)}",
            lambda: self.api.duties_proposer(epoch),
        )

    def post_attester_duties(self, epoch: int, indices):
        return self._post(
            f"/eth/v1/validator/duties/attester/{int(epoch)}",
            lambda: self.api.duties_attester(epoch, indices),
            body=[str(int(i)) for i in indices],
        )

    def produce_block(self, slot: int, randao_reveal: str, graffiti=None):
        q = f"?randao_reveal={randao_reveal}"
        if graffiti:
            q += f"&graffiti={graffiti}"
        return self._get(
            f"/eth/v2/validator/blocks/{int(slot)}{q}",
            lambda: self.api.produce_block(slot, randao_reveal, graffiti),
        )

    def attestation_data(self, slot: int, committee_index: int):
        return self._get(
            f"/eth/v1/validator/attestation_data?slot={int(slot)}"
            f"&committee_index={int(committee_index)}",
            lambda: self.api.attestation_data(slot, committee_index),
        )

    def aggregate_attestation(self, slot: int, attestation_data_root: str):
        return self._get(
            f"/eth/v1/validator/aggregate_attestation?slot={int(slot)}"
            f"&attestation_data_root={attestation_data_root}",
            lambda: self.api.aggregate_attestation(slot, attestation_data_root),
        )

    def post_aggregate_and_proofs(self, aggregates_json):
        return self._post(
            "/eth/v1/validator/aggregate_and_proofs",
            lambda: self.api.publish_aggregate_and_proofs(aggregates_json),
            body=aggregates_json,
        )

    # -------------------------------------------------------- sync committee
    def post_sync_duties(self, epoch: int, indices):
        return self._post(
            f"/eth/v1/validator/duties/sync/{int(epoch)}",
            lambda: self.api.duties_sync(epoch, indices),
            body=[str(int(i)) for i in indices],
        )

    def post_pool_sync_committees(self, messages_json):
        return self._post(
            "/eth/v1/beacon/pool/sync_committees",
            lambda: self.api.pool_sync_committees(messages_json),
            body=messages_json,
        )

    def sync_committee_contribution(self, slot: int, subcommittee_index: int,
                                    beacon_block_root: str):
        return self._get(
            f"/eth/v1/validator/sync_committee_contribution?slot={int(slot)}"
            f"&subcommittee_index={int(subcommittee_index)}"
            f"&beacon_block_root={beacon_block_root}",
            lambda: self.api.sync_committee_contribution(
                slot, subcommittee_index, beacon_block_root
            ),
        )

    def post_contribution_and_proofs(self, contributions_json):
        return self._post(
            "/eth/v1/validator/contribution_and_proofs",
            lambda: self.api.publish_contribution_and_proofs(contributions_json),
            body=contributions_json,
        )
