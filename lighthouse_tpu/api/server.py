"""HTTP adapter for the BeaconApi (reference: warp serve at
http_api/src/lib.rs:256; the metrics server at http_metrics).

A stdlib ``ThreadingHTTPServer`` with a regex route table mapping the
eth2 Beacon-API paths onto ``BeaconApi`` methods, plus `/eth/v1/events`
as Server-Sent Events and an optional `/metrics` Prometheus exposition
hook. Runs on an ephemeral port for tests (`node_test_rig` pattern).
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from .beacon_api import ApiError, BeaconApi

# (method, path regex) -> handler name + path-arg names
ROUTES: list[tuple[str, re.Pattern, str, tuple[str, ...]]] = []


def route(method: str, pattern: str, name: str, args: tuple[str, ...] = ()):
    ROUTES.append((method, re.compile(f"^{pattern}$"), name, args))


route("GET", r"/eth/v1/beacon/genesis", "get_genesis")
route("GET", r"/eth/v1/beacon/states/(?P<state_id>[^/]+)/root", "get_state_root", ("state_id",))
route("GET", r"/eth/v1/beacon/states/(?P<state_id>[^/]+)/fork", "get_state_fork", ("state_id",))
route("GET", r"/eth/v1/beacon/states/(?P<state_id>[^/]+)/finality_checkpoints", "get_finality_checkpoints", ("state_id",))
route("GET", r"/eth/v1/beacon/states/(?P<state_id>[^/]+)/validators", "get_validators", ("state_id",))
route("GET", r"/eth/v1/beacon/states/(?P<state_id>[^/]+)/validators/(?P<validator_id>[^/]+)", "get_validator", ("state_id", "validator_id"))
route("GET", r"/eth/v1/beacon/states/(?P<state_id>[^/]+)/validator_balances", "get_validator_balances", ("state_id",))
route("GET", r"/eth/v1/beacon/states/(?P<state_id>[^/]+)/committees", "get_committees", ("state_id",))
route("GET", r"/eth/v1/beacon/headers", "get_headers")
route("GET", r"/eth/v1/beacon/headers/(?P<block_id>[^/]+)", "get_header", ("block_id",))
route("GET", r"/eth/v2/beacon/blocks/(?P<block_id>[^/]+)", "get_block", ("block_id",))
route("GET", r"/eth/v1/beacon/blocks/(?P<block_id>[^/]+)/root", "get_block_root", ("block_id",))
route("GET", r"/eth/v1/beacon/blocks/(?P<block_id>[^/]+)/attestations", "get_block_attestations", ("block_id",))
route("POST", r"/eth/v1/beacon/blocks", "publish_block")
route("POST", r"/eth/v1/beacon/pool/attestations", "pool_attestations")
route("GET", r"/eth/v1/beacon/pool/attestations", "get_pool_attestations")
route("POST", r"/eth/v1/beacon/pool/voluntary_exits", "pool_voluntary_exit")
route("POST", r"/eth/v1/beacon/pool/proposer_slashings", "pool_proposer_slashings")
route("POST", r"/eth/v1/beacon/pool/attester_slashings", "pool_attester_slashings")
route("POST", r"/eth/v1/beacon/pool/sync_committees", "pool_sync_committees")
route("GET", r"/eth/v1/validator/sync_committee_contribution", "sync_committee_contribution")
route("POST", r"/eth/v1/validator/contribution_and_proofs", "publish_contribution_and_proofs")
route("POST", r"/eth/v1/validator/duties/sync/(?P<epoch>\d+)", "duties_sync", ("epoch",))
route("GET", r"/eth/v2/debug/beacon/states/(?P<state_id>[^/]+)", "get_debug_state", ("state_id",))
route("GET", r"/eth/v1/node/version", "node_version")
route("GET", r"/eth/v1/node/syncing", "node_syncing")
route("GET", r"/eth/v1/node/identity", "node_identity")
route("GET", r"/eth/v1/node/peers", "node_peers")
route("GET", r"/eth/v1/config/spec", "config_spec")
route("GET", r"/eth/v1/config/fork_schedule", "config_fork_schedule")
route("GET", r"/eth/v1/config/deposit_contract", "config_deposit_contract")
route("GET", r"/eth/v1/validator/duties/proposer/(?P<epoch>\d+)", "duties_proposer", ("epoch",))
route("POST", r"/eth/v1/validator/duties/attester/(?P<epoch>\d+)", "duties_attester", ("epoch",))
route("GET", r"/eth/v2/validator/blocks/(?P<slot>\d+)", "produce_block", ("slot",))
route("GET", r"/eth/v1/validator/attestation_data", "attestation_data")
route("GET", r"/eth/v1/validator/aggregate_attestation", "aggregate_attestation")
route("POST", r"/eth/v1/validator/aggregate_and_proofs", "publish_aggregate_and_proofs")
route("POST", r"/eth/v1/validator/beacon_committee_subscriptions", "subscribe_beacon_committee")
route("POST", r"/eth/v1/validator/sync_committee_subscriptions", "subscribe_sync_committee")
route("POST", r"/eth/v1/validator/prepare_beacon_proposer", "prepare_beacon_proposer")
route("GET", r"/lighthouse/syncing", "lighthouse_syncing_state")
route("GET", r"/lighthouse/proto_array", "lighthouse_proto_array")
route("GET", r"/lighthouse/database", "lighthouse_database_info")
route("GET", r"/lighthouse/analysis/block_rewards", "lighthouse_block_rewards")
route("GET", r"/lighthouse/analysis/block_packing_efficiency", "lighthouse_block_packing_efficiency")
route("GET", r"/lighthouse/analysis/attestation_performance/(?P<validator_index>\d+)", "lighthouse_attestation_performance", ("validator_index",))

# handlers whose body is the single positional payload
BODY_AS_PAYLOAD = {
    "publish_block",
    "pool_attestations",
    "pool_voluntary_exit",
    "pool_sync_committees",
    "publish_aggregate_and_proofs",
    "publish_contribution_and_proofs",
    "subscribe_beacon_committee",
    "subscribe_sync_committee",
    "prepare_beacon_proposer",
    "pool_proposer_slashings",
    "pool_attester_slashings",
}
# query params forwarded as keyword arguments (ints where sensible)
QUERY_KWARGS = {
    "get_validators": ("indices",),
    "get_validator_balances": ("indices",),
    "get_committees": ("epoch", "index", "slot"),
    "get_headers": ("slot", "parent_root"),
    "produce_block": ("randao_reveal", "graffiti"),
    "attestation_data": ("slot", "committee_index"),
    "aggregate_attestation": ("slot", "attestation_data_root"),
    "sync_committee_contribution": (
        "slot", "subcommittee_index", "beacon_block_root",
    ),
    "lighthouse_block_rewards": ("start_slot", "end_slot"),
    "lighthouse_block_packing_efficiency": ("start_slot", "end_slot"),
    "lighthouse_attestation_performance": ("start_epoch", "end_epoch"),
}
INT_QUERY_PARAMS = {"epoch", "index", "slot", "committee_index",
                    "subcommittee_index", "start_slot", "end_slot",
                    "start_epoch", "end_epoch"}


class HttpServer:
    """Serve a BeaconApi over HTTP; ephemeral port by default."""

    def __init__(self, api: BeaconApi, host: str = "127.0.0.1", port: int = 0):
        self.api = api
        api_ref = api

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _dispatch(self, method: str):
                parsed = urlparse(self.path)
                if method == "GET" and parsed.path == "/eth/v1/node/health":
                    self.send_response(api_ref.node_health())
                    self.end_headers()
                    return
                if method == "GET" and parsed.path == "/eth/v1/events":
                    return self._serve_events(parsed)
                for m, pattern, name, arg_names in ROUTES:
                    if m != method:
                        continue
                    match = pattern.match(parsed.path)
                    if not match:
                        continue
                    return self._call(name, match, parsed)
                self._respond(404, {"code": 404, "message": "not found"})

            def _call(self, name: str, match, parsed):
                handler = getattr(api_ref, name)
                kwargs = dict(match.groupdict())
                query = {
                    k: v[0] if len(v) == 1 else v
                    for k, v in parse_qs(parsed.query).items()
                }
                for k in QUERY_KWARGS.get(name, ()):
                    if k in query:
                        v = query[k]
                        if k in INT_QUERY_PARAMS:
                            v = int(v)
                        kwargs[k] = v
                if name == "get_validators" and "indices" in kwargs:
                    kwargs["indices"] = [
                        int(x) for x in str(kwargs["indices"]).split(",")
                    ]
                args = []
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                if name in BODY_AS_PAYLOAD or name in ("duties_attester", "duties_sync"):
                    payload = json.loads(body) if body else None
                    if name in ("duties_attester", "duties_sync"):
                        kwargs["indices"] = [int(x) for x in (payload or [])]
                    else:
                        args.append(payload)
                try:
                    result = handler(*args, **kwargs)
                except ApiError as e:
                    return self._respond(e.status, e.body())
                except Exception as e:  # pragma: no cover - defensive
                    return self._respond(500, {"code": 500, "message": repr(e)})
                self._respond(200, result)

            def _serve_events(self, parsed):
                topics = parse_qs(parsed.query).get("topics", ["head"])
                if len(topics) == 1 and "," in topics[0]:
                    topics = topics[0].split(",")
                queue = api_ref.events.subscribe(topics)
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.end_headers()
                # drain whatever is queued, then close (poll-style SSE —
                # deterministic for tests; a long-lived client re-polls)
                for topic, payload in api_ref.events.drain(queue):
                    chunk = f"event: {topic}\ndata: {json.dumps(payload)}\n\n"
                    self.wfile.write(chunk.encode())
                self.wfile.flush()

            def _respond(self, status: int, body: dict):
                data = json.dumps(body).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self._thread: threading.Thread | None = None

    def start(self) -> "HttpServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
