"""eth2 Beacon-API JSON conventions, derived from SSZ schemas.

The reference hand-writes serde impls (`consensus/serde_utils`): uints
as decimal strings, fixed/variable bytes as 0x-hex, bitfields as the
0x-hex of their SSZ encoding, containers as objects. Deriving the codec
from the SSZ schema (which every container already declares) gives the
same wire format without a second type description.
"""

from __future__ import annotations

from ..consensus import ssz


def value_to_json(schema, value):
    if isinstance(schema, ssz.Uint):
        return str(int(value))
    if isinstance(schema, ssz.Boolean):
        return bool(value)
    if isinstance(schema, (ssz.ByteVector, ssz.ByteList)):
        return "0x" + bytes(value).hex()
    if isinstance(schema, (ssz.Bitlist, ssz.Bitvector)):
        return "0x" + schema.encode(list(value)).hex()
    if isinstance(schema, (ssz.List, ssz.Vector)):
        return [value_to_json(schema.elem, v) for v in value]
    if isinstance(schema, ssz._ContainerSchema):
        return container_to_json(value)
    raise TypeError(f"unhandled schema {type(schema).__name__}")


def value_from_json(schema, data):
    if isinstance(schema, ssz.Uint):
        return int(data)
    if isinstance(schema, ssz.Boolean):
        return bool(data)
    if isinstance(schema, (ssz.ByteVector, ssz.ByteList)):
        return bytes.fromhex(str(data).removeprefix("0x"))
    if isinstance(schema, (ssz.Bitlist, ssz.Bitvector)):
        return schema.decode(bytes.fromhex(str(data).removeprefix("0x")))
    if isinstance(schema, (ssz.List, ssz.Vector)):
        return [value_from_json(schema.elem, v) for v in data]
    if isinstance(schema, ssz._ContainerSchema):
        return container_from_json(schema.cls, data)
    raise TypeError(f"unhandled schema {type(schema).__name__}")


def container_to_json(obj) -> dict:
    return {
        name: value_to_json(schema, getattr(obj, name))
        for name, schema in obj.fields.items()
    }


def container_from_json(cls, data: dict):
    kwargs = {
        name: value_from_json(schema, data[name])
        for name, schema in cls.fields.items()
        if name in data
    }
    return cls(**kwargs)
