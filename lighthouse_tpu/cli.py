"""The `lighthouse-tpu` CLI (reference: lighthouse/src/main.rs clap
tree + beacon_node/src/cli.rs + validator_client/src/cli.rs +
account_manager + lcli subcommands).

Subcommands:

* ``bn``        — run a beacon node (interop genesis or checkpoint sync,
  optional HTTP API / metrics / slasher).
* ``vc``        — run a validator client against one or more BNs.
* ``account``   — wallet/validator tooling: keystore create/import/list
  (account_manager).
* ``lcli``      — dev utilities: interop-genesis, skip-slots,
  transition-blocks, parse-ssz, insecure-validators (testing/lcli).
* ``db``        — database inspect/version/migrate/compact (database_manager).
* ``bench``     — the BLS device benchmark (bench.py's workload).
* ``boot-node`` — standalone discovery-only bootnode (boot_node).

Every subcommand melts flags into the component configs exactly as the
reference's get_config does; `--spec minimal|mainnet` picks the preset.
"""

from __future__ import annotations

import argparse
import json
import sys


def _spec_for(name: str):
    from .consensus.config import mainnet_spec, minimal_spec

    return minimal_spec() if name == "minimal" else mainnet_spec()


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--spec", choices=("minimal", "mainnet"), default="mainnet")
    p.add_argument("--debug-level", default="info",
                   choices=("debug", "info", "warn", "error", "crit"))


def build_parser() -> argparse.ArgumentParser:
    root = argparse.ArgumentParser(
        prog="lighthouse-tpu",
        description="TPU-native Ethereum consensus client framework",
    )
    sub = root.add_subparsers(dest="command", required=True)

    bn = sub.add_parser("bn", help="beacon node")
    _add_common(bn)
    bn.add_argument("--datadir", default=None)
    bn.add_argument("--http", action="store_true")
    bn.add_argument("--http-port", type=int, default=5052)
    bn.add_argument("--metrics", action="store_true")
    bn.add_argument("--metrics-port", type=int, default=0)
    bn.add_argument("--validator-monitor-auto", action="store_true")
    bn.add_argument("--slasher", action="store_true")
    bn.add_argument("--interop-validators", type=int, default=64)
    bn.add_argument("--checkpoint-sync-url", default=None)
    bn.add_argument("--boot-nodes", default=None,
                    help="comma-separated bootnode URLs to register with")
    bn.add_argument("--backend", default=None,
                    choices=(None, "python", "jax", "fake"))
    bn.add_argument("--slots", type=int, default=0,
                    help="run N slots then exit (0 = forever)")

    vc = sub.add_parser("vc", help="validator client")
    _add_common(vc)
    vc.add_argument("--beacon-nodes", default="http://127.0.0.1:5052",
                    help="comma-separated BN URLs (fallback order)")
    vc.add_argument("--interop-validators", type=int, default=0,
                    help="use deterministic interop keys [0..N)")
    vc.add_argument("--keystores", nargs="*", default=[],
                    help="EIP-2335 keystore JSON paths")
    vc.add_argument("--password", default="")
    vc.add_argument("--slashing-protection-db", default=":memory:")
    vc.add_argument("--slots", type=int, default=0)

    account = sub.add_parser("account", help="key management")
    _add_common(account)
    asub = account.add_subparsers(dest="action", required=True)
    new = asub.add_parser("new", help="derive + encrypt a validator keystore")
    new.add_argument("--seed-hex", required=True)
    new.add_argument("--index", type=int, default=0)
    new.add_argument("--password", required=True)
    new.add_argument("--out", default="-")
    imp = asub.add_parser("inspect", help="inspect a keystore")
    imp.add_argument("path")
    imp.add_argument("--password", default=None)
    wallet = asub.add_parser(
        "wallet", help="EIP-2386 wallet create/recover/derive"
    )
    wallet.add_argument("action2", choices=("create", "recover", "validator"),
                        metavar="create|recover|validator")
    wallet.add_argument("--name", default="wallet")
    wallet.add_argument("--password", required=True)
    wallet.add_argument("--seed-hex", default=None,
                        help="recover: the seed backup; create: optional")
    wallet.add_argument("--wallet-file", default=None,
                        help="validator: wallet JSON path (updated in place)")
    wallet.add_argument("--keystore-password", default=None)
    wallet.add_argument("--count", type=int, default=1)
    wallet.add_argument("--out", default="-")

    ex = asub.add_parser(
        "exit", help="sign + publish a voluntary exit (validator exit flow)"
    )
    ex.add_argument("--keystore", required=True)
    ex.add_argument("--password", required=True)
    ex.add_argument("--validator-index", type=int, required=True)
    ex.add_argument("--epoch", type=int, required=True)
    ex.add_argument("--beacon-node", default=None,
                    help="BN URL to publish to; omit to just print the exit")
    ex.add_argument("--genesis-validators-root", required=True)
    ex.add_argument("--current-epoch", type=int, default=None,
                    help="the chain's current epoch (fetched from the BN "
                         "when --beacon-node is given; defaults to --epoch)")

    sp = asub.add_parser(
        "slashing-protection", help="EIP-3076 interchange import/export"
    )
    sp.add_argument("action2", choices=("export", "import"),
                    metavar="export|import")
    sp.add_argument("--db", required=True,
                    help="slashing protection SQLite path")
    sp.add_argument("--genesis-validators-root", required=True)
    sp.add_argument("--file", default="-")

    lcli = sub.add_parser("lcli", help="dev utilities")
    _add_common(lcli)
    lsub = lcli.add_subparsers(dest="action", required=True)
    ig = lsub.add_parser("interop-genesis")
    ig.add_argument("--validator-count", type=int, default=64)
    ig.add_argument("--genesis-time", type=int, default=1_600_000_000)
    nt = lsub.add_parser("new-testnet",
                         help="write a network config bundle + genesis state")
    nt.add_argument("--out", required=True, help="output directory")
    nt.add_argument("--validator-count", type=int, default=64)
    nt.add_argument("--genesis-time", type=int, default=1_600_000_000)
    nt.add_argument("--name", default="local-testnet")
    nt.add_argument("--altair-fork-epoch", type=int, default=None)
    nt.add_argument("--bellatrix-fork-epoch", type=int, default=None)
    eg = lsub.add_parser("eth1-genesis",
                         help="genesis state from signed deposits (the "
                              "deposit-contract path)")
    eg.add_argument("--validator-count", type=int, default=16)
    eg.add_argument("--eth1-block-hash", default="0x" + "42" * 32)
    eg.add_argument("--eth1-timestamp", type=int, default=1_606_824_000)
    dd = lsub.add_parser("deploy-deposit-contract",
                         help="deploy the deposit contract over eth1 "
                              "JSON-RPC and optionally submit "
                              "deterministic validator deposits")
    dd.add_argument("--eth1-http", required=True,
                    help="eth1 JSON-RPC endpoint")
    dd.add_argument("--confirmations", type=int, default=1)
    dd.add_argument("--validator-count", type=int, default=None,
                    help="submit deposits for this many insecure "
                         "(interop-key) validators after deploying")
    dd.add_argument("--bytecode-file", default=None,
                    help="compiled contract creation bytecode (hex); "
                         "default is the mock-EL marker payload")
    sk = lsub.add_parser("skip-slots")
    sk.add_argument("--slots", type=int, required=True)
    sk.add_argument("--validator-count", type=int, default=16)
    ps = lsub.add_parser("parse-ssz")
    ps.add_argument("--type", dest="ssz_type", required=True,
                    choices=("attestation", "signed_block", "state"))
    ps.add_argument("path")
    tb = lsub.add_parser("transition-blocks",
                         help="apply SSZ block(s) to an SSZ pre-state")
    tb.add_argument("--pre-state", required=True)
    tb.add_argument("--block", required=True, nargs="+")
    tb.add_argument("--post-state", default=None,
                    help="write the post state SSZ here")
    tb.add_argument("--no-signature-verification", action="store_true")
    iv = lsub.add_parser("insecure-validators",
                         help="write interop keystores + secrets dir")
    iv.add_argument("--count", type=int, required=True)
    iv.add_argument("--base-dir", required=True)

    db = sub.add_parser("db", help="database tooling")
    _add_common(db)
    db.add_argument("--datadir", required=True)
    db.add_argument("action", choices=("inspect", "version", "migrate", "compact"))
    db.add_argument("--target", type=int, default=None,
                    help="migrate: target schema version (default: current)")

    bench = sub.add_parser("bench", help="BLS device benchmark")
    bench.add_argument("--quick", action="store_true")

    boot = sub.add_parser(
        "boot-node", help="standalone discovery-only bootnode (boot_node binary)"
    )
    _add_common(boot)
    boot.add_argument("--port", type=int, default=0)
    boot.add_argument("--host", default="127.0.0.1")

    return root


# ------------------------------------------------------------------ commands
def run_bn(args) -> int:
    from .common.logging import StructuredLogger
    from .common.malloc_utils import configure_memory_allocator
    from .node import ClientBuilder, ClientConfig

    configure_memory_allocator()  # lighthouse/src/main.rs does this first
    log = StructuredLogger(level=args.debug_level)
    spec = _spec_for(args.spec)
    cfg = ClientConfig(
        datadir=args.datadir,
        validator_count=args.interop_validators,
        http_enabled=args.http,
        http_port=args.http_port,
        metrics_enabled=args.metrics,
        metrics_port=args.metrics_port,
        validator_monitor_auto=args.validator_monitor_auto,
        slasher_enabled=args.slasher,
        backend=args.backend,
        manual_clock=args.slots > 0,
    )
    builder = ClientBuilder(cfg, spec, log)
    if args.datadir:
        builder.disk_store(args.datadir)
    else:
        builder.memory_store()
    if args.checkpoint_sync_url:
        from .api import BeaconNodeClient

        builder.checkpoint_sync(BeaconNodeClient(url=args.checkpoint_sync_url))
    else:
        builder.interop_genesis()
    node = builder.build()
    if args.boot_nodes and node.network is not None:
        from .network.discovery import sync_with_boot_node

        for url in args.boot_nodes.split(","):
            try:
                learned = sync_with_boot_node(node.network.discovery, url.strip())
                log.info("bootnode sync", url=url.strip(), learned=learned)
            except (OSError, ValueError, KeyError) as e:
                log.warn("bootnode unusable", url=url.strip(), error=repr(e))
    log.info(
        "beacon node ready",
        spec=args.spec,
        http=node.http.url if node.http else "off",
        head=node.chain.head().root.hex()[:8],
    )
    if args.slots > 0:
        for _ in range(args.slots):
            node.chain.slot_clock.advance_slot()
            node.tick_slot()
        log.info("done", head_slot=int(node.chain.head().block.message.slot))
        node.stop()
        return 0
    node.start()
    node.executor.block_on_shutdown()
    return 0


def run_vc(args) -> int:
    from .api import BeaconNodeClient
    from .common.logging import StructuredLogger
    from .validator import BeaconNodeFallback, SlashingDatabase, ValidatorClient
    from .validator.keystore import Keystore

    log = StructuredLogger(level=args.debug_level)
    spec = _spec_for(args.spec)
    urls = [u.strip() for u in args.beacon_nodes.split(",") if u.strip()]
    clients = [BeaconNodeClient(url=u) for u in urls]
    client = clients[0] if len(clients) == 1 else BeaconNodeFallback(clients)

    genesis = (clients[0].get_genesis())["data"]
    gvr = bytes.fromhex(genesis["genesis_validators_root"].removeprefix("0x"))
    vc = ValidatorClient(
        client, spec, gvr, slashing_db=SlashingDatabase(args.slashing_protection_db)
    )
    if args.interop_validators:
        from .consensus.genesis import interop_keypairs

        vc.add_validators(interop_keypairs(args.interop_validators))
    for path in args.keystores:
        with open(path) as f:
            vc.add_validators([Keystore.from_json(f.read()).decrypt(args.password)])
    log.info("validator client ready", keys=len(vc.store.voting_pubkeys()))

    import time

    seconds = spec.SECONDS_PER_SLOT
    genesis_time = int(genesis["genesis_time"])
    count = 0
    while args.slots == 0 or count < args.slots:
        now = time.time()
        slot = max(0, int(now - genesis_time) // seconds)
        stats = vc.run_slot(slot)
        log.info("slot done", slot=slot, **stats)
        count += 1
        if args.slots == 0:
            time.sleep(max(0.0, (genesis_time + (slot + 1) * seconds) - time.time()))
    return 0


def run_account(args) -> int:
    from .validator.keystore import Keystore, derive_validator_keys

    if args.action == "new":
        seed = bytes.fromhex(args.seed_hex.removeprefix("0x"))
        signing, _ = derive_validator_keys(seed, args.index)
        ks = Keystore.encrypt(
            signing, args.password, path=f"m/12381/3600/{args.index}/0/0"
        )
        out = ks.to_json()
        if args.out == "-":
            print(out)
        else:
            with open(args.out, "w") as f:
                f.write(out)
        return 0
    if args.action == "inspect":
        with open(args.path) as f:
            ks = Keystore.from_json(f.read())
        info = {"pubkey": ks.pubkey, "path": ks.path, "uuid": ks.uuid}
        if args.password is not None:
            ks.decrypt(args.password)
            info["decrypts"] = True
        print(json.dumps(info, indent=2))
        return 0
    if args.action == "wallet":
        from .validator.wallet import Wallet

        if args.action2 in ("create", "recover"):
            seed = (
                bytes.fromhex(args.seed_hex.removeprefix("0x"))
                if args.seed_hex
                else None
            )
            if args.action2 == "recover" and seed is None:
                print(json.dumps({"error": "recover requires --seed-hex"}),
                      file=sys.stderr)
                return 1
            wallet = Wallet.create(args.name, args.password, seed=seed)
            # round-trip guard: the wallet must decrypt back to the seed
            recovered = wallet.decrypt_seed(args.password)
            if seed is not None and recovered != seed:
                print(json.dumps({"error": "seed round-trip failed"}),
                      file=sys.stderr)
                return 1
            out = wallet.to_json()
            if args.out == "-":
                print(out)
            else:
                with open(args.out, "w") as f:
                    f.write(out)
            if args.action2 == "create" and args.seed_hex is None:
                # the backup material (the reference prints a mnemonic)
                print(json.dumps({"seed_backup": "0x" + recovered.hex()}),
                      file=sys.stderr)
            return 0
        # action2 == "validator": derive the next N keystores
        if not args.wallet_file or not args.keystore_password:
            print(json.dumps({"error": "--wallet-file and "
                              "--keystore-password required"}),
                  file=sys.stderr)
            return 1
        with open(args.wallet_file) as f:
            wallet = Wallet.from_json(f.read())
        keystores = [
            json.loads(
                wallet.next_validator(
                    args.password, args.keystore_password
                ).to_json()
            )
            for _ in range(args.count)
        ]
        with open(args.wallet_file, "w") as f:
            f.write(wallet.to_json())  # persists nextaccount
        out = json.dumps(keystores, indent=2)
        if args.out == "-":
            print(out)
        else:
            with open(args.out, "w") as f:
                f.write(out)
        return 0
    if args.action == "exit":
        from .api import BeaconNodeClient
        from .consensus.config import compute_signing_root
        from .consensus.types import SignedVoluntaryExit, VoluntaryExit
        from .validator.keystore import Keystore

        with open(args.keystore) as f:
            sk = Keystore.from_json(f.read()).decrypt(args.password)
        spec = _spec_for(args.spec)
        gvr = bytes.fromhex(args.genesis_validators_root.removeprefix("0x"))
        msg = VoluntaryExit(
            epoch=args.epoch, validator_index=args.validator_index
        )
        # Sign under the Fork container the chain CURRENTLY carries (the
        # verifier's get_domain picks previous_version for pre-fork exit
        # epochs — two forks later that is NOT the exit epoch's own
        # version). Prefer the BN's live view of the current epoch.
        current_epoch = args.current_epoch
        if current_epoch is None and args.beacon_node:
            head = BeaconNodeClient(url=args.beacon_node).get_header()
            slot = int(head["data"]["header"]["message"]["slot"])
            current_epoch = slot // spec.preset.SLOTS_PER_EPOCH
        if current_epoch is None:
            current_epoch = args.epoch
        domain = spec.get_domain(
            spec.DOMAIN_VOLUNTARY_EXIT,
            args.epoch,
            spec.fork_at_epoch(current_epoch),
            gvr,
        )
        signed = SignedVoluntaryExit(
            message=msg,
            signature=sk.sign(compute_signing_root(msg, domain)).to_bytes(),
        )
        exit_json = {
            "message": {
                "epoch": str(args.epoch),
                "validator_index": str(args.validator_index),
            },
            "signature": "0x" + bytes(signed.signature).hex(),
        }
        if args.beacon_node:
            BeaconNodeClient(url=args.beacon_node).post_voluntary_exit(
                exit_json
            )
            print(json.dumps({"published": True, **exit_json}))
        else:
            print(json.dumps(exit_json, indent=2))
        return 0
    if args.action == "slashing-protection":
        from .validator.slashing_protection import SlashingDatabase

        db = SlashingDatabase(args.db)
        gvr = bytes.fromhex(
            args.genesis_validators_root.removeprefix("0x")
        )
        if args.action2 == "export":
            out = json.dumps(db.export_interchange(gvr), indent=2)
            if args.file == "-":
                print(out)
            else:
                with open(args.file, "w") as f:
                    f.write(out)
            return 0
        if args.file == "-":
            data = sys.stdin.read()
        else:
            with open(args.file) as f:
                data = f.read()
        from .validator.slashing_protection import SlashingError

        try:
            count = db.import_interchange(data, gvr)
        except SlashingError as e:
            print(json.dumps({"error": str(e)}), file=sys.stderr)
            return 1
        print(json.dumps({"imported_validators": count}))
        return 0
    return 1


def run_lcli(args) -> int:
    from .chain.harness import BeaconChainHarness

    spec = _spec_for(args.spec)
    if args.action == "interop-genesis":
        from .consensus.genesis import interop_genesis_state, interop_keypairs
        from .crypto.bls import backends as bls_backends

        prev = bls_backends._default
        bls_backends.set_default_backend("fake")
        try:
            state = interop_genesis_state(
                interop_keypairs(args.validator_count), args.genesis_time, spec,
                sign_deposits=False,
            )
        finally:
            bls_backends._default = prev
        print(json.dumps({
            "genesis_validators_root": "0x"
            + bytes(state.genesis_validators_root).hex(),
            "genesis_time": int(state.genesis_time),
            "validators": len(state.validators),
        }))
        return 0
    if args.action == "new-testnet":
        # lcli new_testnet: a network config bundle another node can boot
        # from (eth2_network_config layout: config.yaml + genesis.ssz +
        # boot_enr.yaml; network_config.load_testnet_dir reads it back).
        import os

        from .consensus.genesis import interop_genesis_state, interop_keypairs
        from .crypto.bls import backends as bls_backends

        prev = bls_backends._default
        bls_backends.set_default_backend("fake")
        try:
            state = interop_genesis_state(
                interop_keypairs(args.validator_count), args.genesis_time,
                spec, sign_deposits=False,
            )
        finally:
            bls_backends._default = prev
        os.makedirs(args.out, exist_ok=True)
        config = {
            "CONFIG_NAME": args.name,
            "PRESET_BASE": args.spec,
            "MIN_GENESIS_ACTIVE_VALIDATOR_COUNT": args.validator_count,
            "MIN_GENESIS_TIME": args.genesis_time,
            "GENESIS_FORK_VERSION": "0x" + spec.GENESIS_FORK_VERSION.hex(),
            "SECONDS_PER_SLOT": spec.SECONDS_PER_SLOT,
        }
        if args.altair_fork_epoch is not None:
            config["ALTAIR_FORK_EPOCH"] = args.altair_fork_epoch
            config["ALTAIR_FORK_VERSION"] = (
                "0x" + spec.ALTAIR_FORK_VERSION.hex()
            )
        if args.bellatrix_fork_epoch is not None:
            config["BELLATRIX_FORK_EPOCH"] = args.bellatrix_fork_epoch
            config["BELLATRIX_FORK_VERSION"] = (
                "0x" + spec.BELLATRIX_FORK_VERSION.hex()
            )
        with open(os.path.join(args.out, "config.yaml"), "w") as f:
            for k, v in config.items():
                f.write(f"{k}: {v}\n")
        with open(os.path.join(args.out, "genesis.ssz"), "wb") as f:
            f.write(state.encode())
        with open(os.path.join(args.out, "boot_enr.yaml"), "w") as f:
            f.write("[]\n")
        print(json.dumps({
            "out": args.out,
            "genesis_validators_root": "0x"
            + bytes(state.genesis_validators_root).hex(),
            "validators": len(state.validators),
        }))
        return 0
    if args.action == "deploy-deposit-contract":
        # lcli deploy_deposit_contract (reference: lcli/src/
        # deploy_deposit_contract.rs): deploy over eth1 JSON-RPC, wait
        # confirmations, print the address, then optionally submit
        # deterministic insecure-validator deposits.
        from .execution.deposit_contract import (
            MOCK_DEPOSIT_RUNTIME,
            DepositContractClient,
            DepositContractError,
        )

        client = DepositContractClient(args.eth1_http)
        try:
            bytecode = MOCK_DEPOSIT_RUNTIME
            if args.bytecode_file:
                try:
                    with open(args.bytecode_file) as f:
                        bytecode = bytes.fromhex(
                            f.read().strip().removeprefix("0x")
                        )
                except (OSError, ValueError) as e:
                    raise DepositContractError(
                        f"bytecode file {args.bytecode_file}: {e}"
                    ) from e
            address = client.deploy(bytecode, args.confirmations)
            print(f"Deposit contract address: {address}")
            if args.validator_count:
                amount = spec.preset.MAX_EFFECTIVE_BALANCE
                for i in range(args.validator_count):
                    print(f"Submitting deposit for validator {i}...")
                    client.deposit_deterministic(address, i, amount, spec)
        except DepositContractError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        return 0
    if args.action == "eth1-genesis":
        # lcli eth1_genesis: the deposit-contract path — REAL signed
        # deposits through initialize_beacon_state_from_eth1.
        from .consensus.genesis import (
            genesis_deposits,
            initialize_beacon_state_from_eth1,
            interop_keypairs,
        )

        keys = interop_keypairs(args.validator_count)
        deposits = genesis_deposits(
            keys, spec.preset.MAX_EFFECTIVE_BALANCE, spec, sign=True
        )
        state = initialize_beacon_state_from_eth1(
            bytes.fromhex(args.eth1_block_hash.removeprefix("0x")),
            args.eth1_timestamp,
            deposits,
            spec,
        )
        print(json.dumps({
            "genesis_validators_root": "0x"
            + bytes(state.genesis_validators_root).hex(),
            "validators": len(state.validators),
            "genesis_time": int(state.genesis_time),
        }))
        return 0
    if args.action == "skip-slots":
        h = BeaconChainHarness(validator_count=args.validator_count, spec=spec)
        from .consensus.transition.slot import process_slots

        state = process_slots(
            h.chain.head().state.copy(), args.slots, h.spec
        )
        print(json.dumps({
            "slot": int(state.slot),
            "state_root": "0x" + state.hash_tree_root().hex(),
        }))
        return 0
    if args.action == "parse-ssz":
        from .consensus.types import spec_types

        t = spec_types(spec.preset)
        with open(args.path, "rb") as f:
            raw = f.read()
        cls = {
            "attestation": t.Attestation,
            "signed_block": t.SIGNED_BLOCK_BY_FORK["phase0"],
            "state": t.BeaconStatePhase0,
        }[args.ssz_type]
        from .api.json_codec import container_to_json

        print(json.dumps(container_to_json(cls.decode(raw)), indent=2))
        return 0
    if args.action == "transition-blocks":
        # lcli/src/transition_blocks.rs: replay blocks onto a pre-state
        from .consensus.transition.block import (
            SignatureStrategy,
            per_block_processing,
        )
        from .consensus.transition.slot import process_slots
        from .consensus.types import spec_types, state_fork_name

        t = spec_types(spec.preset)
        with open(args.pre_state, "rb") as f:
            raw = f.read()
        # the SSZ state has no self-describing tag: pick the fork class
        # whose schema round-trips (newest first — later forks are
        # supersets and would mis-decode under older schemas)
        state = None
        for fork in ("bellatrix", "altair", "phase0"):
            try:
                candidate = t.STATE_BY_FORK[fork].decode(raw)
                if spec.fork_name_at_epoch(
                    int(candidate.slot) // spec.preset.SLOTS_PER_EPOCH
                ) == fork:
                    state = candidate
                    break
            except Exception:  # lhtpu: ignore[LH502] -- probing candidate pre-state decodings; failures mean try the next fork
                continue
        if state is None:
            print(json.dumps({"error": "undecodable pre-state"}),
                  file=sys.stderr)
            return 1
        strategy = (
            SignatureStrategy.NO_VERIFICATION
            if args.no_signature_verification
            else SignatureStrategy.VERIFY_BULK
        )
        for path in args.block:
            with open(path, "rb") as f:
                block_raw = f.read()
            # message.slot: first field of the message, which starts at
            # the 4-byte variable-offset recorded at the front
            msg_off = int.from_bytes(block_raw[:4], "little")
            slot = int.from_bytes(block_raw[msg_off:msg_off + 8], "little")
            if int(state.slot) < slot:
                state = process_slots(state, slot, spec)
            # block class chosen AFTER the advance (fork upgrades happen
            # at epoch boundaries inside process_slots)
            signed = t.SIGNED_BLOCK_BY_FORK[state_fork_name(state)].decode(
                block_raw
            )
            per_block_processing(state, signed, spec, strategy=strategy)
        out = state.encode()
        if args.post_state:
            with open(args.post_state, "wb") as f:
                f.write(out)
        print(json.dumps({
            "slot": int(state.slot),
            "state_root": "0x" + state.hash_tree_root().hex(),
        }))
        return 0
    if args.action == "insecure-validators":
        # lcli insecure_validators: deterministic interop keys, encrypted
        # under a per-key password file (validator_dir layout)
        import os

        from .consensus.genesis import interop_keypairs
        from .validator.keystore import Keystore

        os.makedirs(os.path.join(args.base_dir, "validators"), exist_ok=True)
        os.makedirs(os.path.join(args.base_dir, "secrets"), exist_ok=True)
        for i, sk in enumerate(interop_keypairs(args.count)):
            pubkey = sk.public_key().to_bytes().hex()
            password = f"insecure-password-{i}"
            ks = Keystore.encrypt(sk, password, kdf="pbkdf2",
                                  path=f"m/12381/3600/{i}/0/0")
            vdir = os.path.join(args.base_dir, "validators", f"0x{pubkey}")
            os.makedirs(vdir, exist_ok=True)
            with open(os.path.join(vdir, "voting-keystore.json"), "w") as f:
                f.write(ks.to_json())
            with open(
                os.path.join(args.base_dir, "secrets", f"0x{pubkey}"), "w"
            ) as f:
                f.write(password)
        print(json.dumps({"validators_written": args.count,
                          "base_dir": args.base_dir}))
        return 0
    return 1


def run_db(args) -> int:
    """database_manager equivalents: inspect / version / migrate /
    compact (database_manager/src/lib.rs subcommands)."""
    from .store.kv import KVStore
    from .store.schema_change import (
        CURRENT_SCHEMA_VERSION,
        migrate_schema,
        read_schema_version,
    )

    store = KVStore(args.datadir)
    try:
        if args.action == "version":
            print(json.dumps({
                "schema_version": read_schema_version(store),
                "current": CURRENT_SCHEMA_VERSION,
            }))
            return 0
        if args.action == "migrate":
            target = (args.target if args.target is not None
                      else CURRENT_SCHEMA_VERSION)
            version = migrate_schema(store, target)
            print(json.dumps({"schema_version": version}))
            return 0
        if args.action == "compact":
            store.compact()
            print(json.dumps({"compacted": True}))
            return 0
        counts: dict[str, int] = {}
        for column in (b"blk", b"ste", b"sum", b"met"):
            counts[column.decode()] = sum(
                1 for _ in store.iter_keys(column)
            )
        print(json.dumps(counts))
        return 0
    finally:
        store.close()


def run_bench(args) -> int:
    import subprocess

    cmd = [sys.executable, "bench.py"] + (["--quick"] if args.quick else [])
    return subprocess.call(cmd)


def run_boot_node(args) -> int:
    from .common.logging import StructuredLogger
    from .network.discovery import BootNodeServer

    log = StructuredLogger(level=args.debug_level)
    server = BootNodeServer(host=args.host, port=args.port)
    log.info("boot node listening", url=server.url)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.stop()
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return {
        "bn": run_bn,
        "vc": run_vc,
        "account": run_account,
        "lcli": run_lcli,
        "db": run_db,
        "bench": run_bench,
        "boot-node": run_boot_node,
    }[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
