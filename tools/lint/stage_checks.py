"""LH3xx — stage/metric-name coherence.

The stage grammar lives in ``lighthouse_tpu/common/stages.py``
(``CANONICAL_STAGES``); four subsystems consume it — the dispatch
timers (``_stage``/``_retry_stage``), the
``bls_dispatch_stage_seconds{stage}`` /
``bls_dispatch_errors_total{stage}`` metric labels, the resilience
fault-injection spec (``LHTPU_FAULT_INJECT=stage:kind:count``), and the
soak chaos schedule (``epoch:stage:kind:count``). A typo'd stage name
silently times nothing / injects nothing, so every LITERAL stage string
is cross-checked here:

* LH301  literal stage argument (positional to
         ``_stage``/``_retry_stage``/``maybe_inject``, or any
         ``stage=`` keyword) not in the canonical list
* LH302  fault-inject / chaos-schedule literal whose stage token is
         not canonical
* LH303  a module-level ``*STAGES`` tuple/list containing a
         non-canonical stage
"""

from __future__ import annotations

import ast

from .core import Ctx, FileCtx

STAGES_REL = "lighthouse_tpu/common/stages.py"

#: callables whose first positional argument is a stage name
_STAGE_ARG0 = {"_stage", "_retry_stage", "maybe_inject"}


def canonical_stages(ctx: Ctx) -> frozenset[str]:
    """CANONICAL_STAGES read straight off the AST of stages.py — the
    linter never imports analyzed code."""
    f = ctx.by_rel(STAGES_REL)
    if f is None:
        try:
            import os
            with open(os.path.join(ctx.root, STAGES_REL),
                      encoding="utf-8") as fh:
                f = FileCtx(ctx.root, STAGES_REL, fh.read())
        except (OSError, SyntaxError):
            return frozenset()
    for node in ast.walk(f.tree):
        target = None
        if isinstance(node, ast.Assign) and node.targets:
            target = node.targets[0]
        elif isinstance(node, ast.AnnAssign):  # CANONICAL_STAGES: tuple
            target = node.target
        if (target is not None and isinstance(target, ast.Name)
                and target.id == "CANONICAL_STAGES"
                and isinstance(node.value, (ast.Tuple, ast.List))):
            return frozenset(
                el.value for el in node.value.elts
                if isinstance(el, ast.Constant)
                and isinstance(el.value, str)
            )
    return frozenset()


def _callee_tail(fn) -> str | None:
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _str_const(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _leading_literal(node) -> str | None:
    """The literal prefix of a spec expression: plain string constant,
    or the first constant piece of an f-string
    (``f"dispatch:{kind}:1"`` -> ``"dispatch:"``)."""
    if (s := _str_const(node)) is not None:
        return s
    if isinstance(node, ast.JoinedStr) and node.values:
        return _str_const(node.values[0])
    return None


def _check_spec(ctx: Ctx, f: FileCtx, lineno: int, env: str,
                literal: str, canon: frozenset[str]) -> None:
    """Validate stage tokens in a FAULT_INJECT/CHAOS_SCHEDULE literal."""
    stage_index = 0 if env == "LHTPU_FAULT_INJECT" else 1
    for item in filter(None, (p.strip() for p in literal.split(";"))):
        for sub in filter(None, (p.strip() for p in item.split(","))):
            fields = sub.split(":")
            if len(fields) <= stage_index:
                continue  # partial f-string prefix without the token
            stage = fields[stage_index]
            if stage and stage not in canon:
                ctx.add(
                    f, lineno, "LH302",
                    f"{env} literal names unknown stage {stage!r} "
                    f"(canonical: {', '.join(sorted(canon))})",
                )


def _spec_env_name(target) -> str | None:
    """``os.environ["LHTPU_FAULT_INJECT"]`` assignment target -> env."""
    if (isinstance(target, ast.Subscript)
            and isinstance(target.value, ast.Attribute)
            and target.value.attr == "environ"):
        name = _str_const(target.slice)
        if name in ("LHTPU_FAULT_INJECT", "LHTPU_CHAOS_SCHEDULE"):
            return name
    return None


def run(ctx: Ctx) -> None:
    canon = canonical_stages(ctx)
    if not canon:
        return

    for f in ctx.files:
        if f.rel == STAGES_REL:
            continue
        # tests exercise the machinery with made-up stage names on
        # purpose; only shipped code + lh3 fixtures are held to the
        # grammar
        if (f.rel.startswith("tests/")
                and f.fixture_family != "lh3"):
            continue
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Call):
                # positional stage arg
                if (_callee_tail(node.func) in _STAGE_ARG0
                        and node.args):
                    s = _str_const(node.args[0])
                    if s is not None and s not in canon:
                        ctx.add(
                            f, node.lineno, "LH301",
                            f"stage {s!r} is not canonical (see "
                            f"{STAGES_REL})",
                        )
                # stage= keyword anywhere (metric labels, retries)
                for kw in node.keywords:
                    if kw.arg == "stage":
                        s = _str_const(kw.value)
                        if s is not None and s not in canon:
                            ctx.add(
                                f, node.lineno, "LH301",
                                f"stage={s!r} is not canonical (see "
                                f"{STAGES_REL})",
                            )
                # scoped_env({"LHTPU_FAULT_INJECT": "..."}) and friends
                for arg in list(node.args) + [
                        kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Dict):
                        for k, v in zip(arg.keys, arg.values):
                            env = _str_const(k)
                            if env not in ("LHTPU_FAULT_INJECT",
                                           "LHTPU_CHAOS_SCHEDULE"):
                                continue
                            lit = _leading_literal(v)
                            if lit:
                                _check_spec(ctx, f, v.lineno, env, lit,
                                            canon)
            elif isinstance(node, ast.Assign):
                for target in node.targets:
                    env = _spec_env_name(target)
                    if env is not None:
                        lit = _leading_literal(node.value)
                        if lit:
                            _check_spec(ctx, f, node.lineno, env, lit,
                                        canon)
                    # module-level FOO_STAGES = ("pack", ...)
                    elif (isinstance(target, ast.Name)
                          and target.id.endswith("STAGES")
                          and isinstance(node.value,
                                         (ast.Tuple, ast.List))):
                        for el in node.value.elts:
                            s = _str_const(el)
                            if s is not None and s not in canon:
                                ctx.add(
                                    f, el.lineno, "LH303",
                                    f"{target.id} contains "
                                    f"non-canonical stage {s!r} (see "
                                    f"{STAGES_REL})",
                                )
