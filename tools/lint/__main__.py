"""CLI: ``python -m tools.lint [--json] [--changed-only] [paths...]``.

Exit status 0 = clean, 1 = findings (so it slots straight into CI).
``--knob-table`` prints the generated README knob table and exits —
paste it between the ``<!-- knob-table:begin/end -->`` markers (LH203
fails the lint while the checked-in copy is stale).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .core import LINT_VERSION, changed_files, run_lint
from .knobs_checks import load_knobs_module


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools.lint",
        description="lhtpu invariant checker (pure stdlib-ast; no JAX)",
    )
    ap.add_argument("paths", nargs="*",
                    help="repo-relative .py files (default: whole tree)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings on stdout")
    ap.add_argument("--changed-only", action="store_true",
                    help="lint only files from git diff + untracked")
    ap.add_argument("--knob-table", action="store_true",
                    help="print the generated README knob table and exit")
    ap.add_argument("--root", default=None,
                    help="repo root (default: cwd, or the tree "
                         "containing this package)")
    args = ap.parse_args(argv)

    root = args.root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

    if args.knob_table:
        mod = load_knobs_module(root)
        if mod is None:
            print("error: could not load lighthouse_tpu/common/knobs.py",
                  file=sys.stderr)
            return 2
        print(mod.knob_table_markdown())
        return 0

    files: list[str] | None = None
    if args.paths:
        files = args.paths
    elif args.changed_only:
        files = changed_files(root)
        if not files:
            if args.as_json:
                print(json.dumps({"version": LINT_VERSION,
                                  "findings": []}))
            else:
                print("lhtpu-lint: no changed .py files")
            return 0

    findings = run_lint(root, files=files)

    if args.as_json:
        print(json.dumps({
            "version": LINT_VERSION,
            "findings": [fi.as_dict() for fi in findings],
        }, indent=2))
    else:
        for fi in findings:
            print(fi.render())
        print(f"lhtpu-lint {LINT_VERSION}: "
              f"{len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
