"""LH5xx — resilience hygiene.

The resilience layer only works when failures actually REACH it: a
handler that eats an exception without recording anything starves the
breaker/classifier of the signal it exists to consume.

* LH501  bare ``except:`` — catches KeyboardInterrupt/SystemExit too
* LH502  ``except Exception/BaseException`` whose body neither
         re-raises nor CALLS anything — a pure swallow (``pass``,
         ``return None``, constant assignment). Handlers that record
         (metric bump, classify, log write) pass; genuinely best-effort
         swallows carry a waiver with the justification.
* LH503  mutable default argument (``def f(x=[])``) — shared across
         calls, a classic slow corruption
"""

from __future__ import annotations

import ast

from .core import Ctx, FileCtx

_BROAD = {"Exception", "BaseException"}


def _catches_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [el.id for el in t.elts if isinstance(el, ast.Name)]
    return any(n in _BROAD for n in names)


def _body_acts(handler: ast.ExceptHandler) -> bool:
    """True when the handler re-raises, calls ANYTHING, or stores the
    bound exception somewhere (``box["error"] = exc``) — i.e. the
    failure leaves a trace."""
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.Call)):
            return True
        if (handler.name and isinstance(node, ast.Name)
                and node.id == handler.name
                and isinstance(node.ctx, ast.Load)):
            return True
    return False


def _mutable_default(node) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set)):
        return True
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id in ("list", "dict", "set"))


def _check_file(ctx: Ctx, f: FileCtx) -> None:
    for node in ast.walk(f.tree):
        if isinstance(node, ast.ExceptHandler):
            if node.type is None:
                ctx.add(
                    f, node.lineno, "LH501",
                    "bare 'except:' — also catches "
                    "KeyboardInterrupt/SystemExit; name the exception "
                    "(at minimum 'except Exception')",
                )
            elif _catches_broad(node) and not _body_acts(node):
                ctx.add(
                    f, node.lineno, "LH502",
                    "broad except swallows the failure without "
                    "recording anything — route it (resilience."
                    "classify, a metric bump, a log line) or waive "
                    "with justification",
                )
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defaults = (list(node.args.defaults)
                        + [d for d in node.args.kw_defaults
                           if d is not None])
            for d in defaults:
                if _mutable_default(d):
                    ctx.add(
                        f, d.lineno, "LH503",
                        f"mutable default argument in {node.name!r} — "
                        f"shared across calls; use None + init in body",
                    )


def run(ctx: Ctx) -> None:
    for f in ctx.files:
        # test code swallows on purpose constantly (pytest.raises
        # scaffolding, teardown best-effort); hold shipped code + the
        # tools layer + the lh5 fixtures to the standard
        if (f.rel.startswith("tests/")
                and f.fixture_family != "lh5"):
            continue
        _check_file(ctx, f)
