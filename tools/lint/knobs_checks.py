"""LH2xx — env-knob registry coherence.

* LH201  raw ``os.environ``/``os.getenv`` READ of a literal ``LHTPU_*``
         name outside ``lighthouse_tpu/common/knobs.py``. Writes
         (assignment, ``setdefault``, ``pop``, ``del``) stay legal —
         tests and drills must still be able to flip knobs; only the
         *parse* must be centralized.
* LH202  a literal ``LHTPU_*`` name passed together with a literal
         default to anything but the registry accessors — a second
         declaration of a default that already lives in the registry.
* LH203  the README knob table no longer matches
         ``knob_table_markdown()`` (regenerate with
         ``python -m tools.lint --knob-table``). Full-tree mode only.
* LH204  ``knob(...)``/``raw(...)`` called with an unregistered literal
         name (would KeyError at runtime / silently bypass typing).
* LH205  a registered knob whose name appears in no consumer file —
         a dead knob rotting in the registry. Full-tree mode only.
"""

from __future__ import annotations

import ast
import importlib.util
import os
import sys

from .core import Ctx, FileCtx

KNOBS_REL = "lighthouse_tpu/common/knobs.py"
README_REL = "README.md"
TABLE_BEGIN = "<!-- knob-table:begin (generated: python -m tools.lint --knob-table) -->"
TABLE_END = "<!-- knob-table:end -->"

#: registry accessors — literal names passed to these are the POINT,
#: not a duplication
_ACCESSORS = {"knob", "raw", "maybe_int", "scoped_env"}


def load_knobs_module(root: str):
    """Execute knobs.py in isolation (stdlib-only module; no package
    import, no JAX) and return it, or None when absent/broken."""
    path = os.path.join(root, KNOBS_REL)
    if not os.path.exists(path):
        return None
    try:
        spec = importlib.util.spec_from_file_location("_lhtpu_knobs", path)
        mod = importlib.util.module_from_spec(spec)
        # dataclasses resolves annotations through sys.modules during
        # exec — register for the duration, then drop
        sys.modules["_lhtpu_knobs"] = mod
        try:
            spec.loader.exec_module(mod)
        finally:
            sys.modules.pop("_lhtpu_knobs", None)
        return mod
    except Exception as exc:
        sys.stderr.write(f"lhtpu-lint: knobs.py failed to load: {exc!r}; "
                         f"LH2xx registry checks degraded\n")
        return None


def _is_lhtpu_literal(node) -> str | None:
    if (isinstance(node, ast.Constant) and isinstance(node.value, str)
            and node.value.startswith("LHTPU_")):
        return node.value
    return None


def _is_environ(node) -> bool:
    """``os.environ`` (or any ``<x>.environ``) attribute access."""
    return isinstance(node, ast.Attribute) and node.attr == "environ"


def _check_file(ctx: Ctx, f: FileCtx, registered: set[str],
                check_duplicated_defaults: bool) -> None:
    for node in ast.walk(f.tree):
        # -- LH201: reads -------------------------------------------------
        if isinstance(node, ast.Call):
            fn = node.func
            name = None
            if node.args:
                name = _is_lhtpu_literal(node.args[0])
            if name and isinstance(fn, ast.Attribute):
                # os.environ.get("LHTPU_X"[, d]) — a read.
                # pop/setdefault mutate the env: they are the
                # write-side API tests/drills legitimately use.
                if _is_environ(fn.value) and fn.attr == "get":
                    ctx.add(
                        f, node.lineno, "LH201",
                        f"raw os.environ read of {name!r}; use "
                        f"knobs.knob()/knobs.raw() (registry: {KNOBS_REL})",
                    )
                # os.getenv("LHTPU_X")
                elif fn.attr == "getenv":
                    ctx.add(
                        f, node.lineno, "LH201",
                        f"raw os.getenv read of {name!r}; use "
                        f"knobs.knob()/knobs.raw()",
                    )
            # -- LH202/LH204: literal name into a helper ------------------
            callee = (
                fn.id if isinstance(fn, ast.Name)
                else fn.attr if isinstance(fn, ast.Attribute) else None
            )
            if name and callee in ("knob", "raw") and name not in registered:
                ctx.add(
                    f, node.lineno, "LH204",
                    f"knobs.{callee}({name!r}): name not in the "
                    f"registry — register it in {KNOBS_REL}",
                )
            elif (
                check_duplicated_defaults
                and callee is not None
                and callee not in _ACCESSORS
                and not (isinstance(fn, ast.Attribute)
                         and (_is_environ(fn.value) or fn.attr == "getenv"))
            ):
                # any registered literal name + any sibling literal
                # constant = a default declared outside the registry
                lh_args = [
                    v for a in node.args
                    if (v := _is_lhtpu_literal(a)) and v in registered
                ]
                # a duplicated default is a NUMBER/bool riding along;
                # sibling strings (cache names, doc) are fine
                others = [
                    a for a in node.args
                    if isinstance(a, ast.Constant)
                    and isinstance(a.value, (bool, int, float))
                ]
                if lh_args and others:
                    ctx.add(
                        f, node.lineno, "LH202",
                        f"{callee}({lh_args[0]!r}, ...) passes a literal "
                        f"default alongside a registered knob name — the "
                        f"default belongs in {KNOBS_REL} only",
                    )
        # -- LH201: subscript read os.environ["LHTPU_X"] ------------------
        elif isinstance(node, ast.Subscript):
            name = _is_lhtpu_literal(node.slice)
            if (name and _is_environ(node.value)
                    and isinstance(node.ctx, ast.Load)):
                ctx.add(
                    f, node.lineno, "LH201",
                    f"raw os.environ[{name!r}] read; use knobs.knob()",
                )
        # -- LH201: membership test "LHTPU_X" in os.environ ---------------
        elif isinstance(node, ast.Compare):
            name = _is_lhtpu_literal(node.left)
            if name and any(
                isinstance(op, (ast.In, ast.NotIn)) and _is_environ(cmp)
                for op, cmp in zip(node.ops, node.comparators)
            ):
                ctx.add(
                    f, node.lineno, "LH201",
                    f"membership test {name!r} in os.environ; use "
                    f"knobs.raw({name!r}) is not None",
                )


def run(ctx: Ctx) -> None:
    mod = load_knobs_module(ctx.root)
    registered: set[str] = set(mod.REGISTRY) if mod is not None else set()

    for f in ctx.files:
        if f.rel == KNOBS_REL:
            continue
        if f.in_fixture_dir and f.fixture_family != "lh2":
            continue
        # tests legitimately re-declare values via monkeypatch.setenv;
        # only non-test code is held to single-declaration (fixtures
        # opt back in so the golden test can exercise LH202)
        dup = not f.rel.startswith("tests/") or f.in_fixture_dir
        _check_file(ctx, f, registered, dup)

    if not ctx.full_tree or mod is None:
        return

    # -- LH203: README table staleness ------------------------------------
    readme_path = os.path.join(ctx.root, README_REL)
    try:
        with open(readme_path, "r", encoding="utf-8") as fh:
            readme = fh.read()
    except OSError:
        readme = ""
    begin, end = readme.find(TABLE_BEGIN), readme.find(TABLE_END)
    anchor = FileCtx(ctx.root, README_REL, "")  # waivers n/a for .md
    if begin < 0 or end < 0 or end < begin:
        ctx.add(
            anchor, 1, "LH203",
            f"README is missing the generated knob table between "
            f"{TABLE_BEGIN!r} and {TABLE_END!r} markers",
        )
    else:
        checked_in = readme[begin + len(TABLE_BEGIN):end].strip()
        line = readme[:begin].count("\n") + 1
        if checked_in != mod.knob_table_markdown().strip():
            ctx.add(
                anchor, line, "LH203",
                "README knob table is stale — regenerate with "
                "'python -m tools.lint --knob-table' and paste between "
                "the markers",
            )

    # -- LH205: dead knobs -------------------------------------------------
    knobs_ctx = ctx.by_rel(KNOBS_REL)
    for name, k in mod.REGISTRY.items():
        quoted = (f'"{name}"', f"'{name}'")
        alive = any(
            f.rel != KNOBS_REL and any(q in f.source for q in quoted)
            for f in ctx.files
        )
        if not alive and knobs_ctx is not None:
            line = next(
                (i for i, text in
                 enumerate(knobs_ctx.source.splitlines(), start=1)
                 if f'"{name}"' in text),
                1,
            )
            ctx.add(
                knobs_ctx, line, "LH205",
                f"registered knob {name} has no consumer (no file "
                f"mentions it) — delete it or wire it up",
            )
