"""LH1xx — jit-purity.

Roots are functions handed to ``jax.jit`` / ``shard_map`` /
``pallas_call`` (direct call, wrapped call like
``jax.jit(_gathered(_verify_core))``, or via a ``@jax.jit`` /
``@partial(shard_map, ...)`` decorator). From each root we BFS the
MODULE-LOCAL call graph (cross-module helpers are linted when their own
module's roots reach them) and flag host-side impurity inside anything
reachable:

* LH101  ``time.*`` call — wall-clock baked in at trace time
* LH102  ``os.environ`` / ``os.getenv`` — env read under trace caches
         one process's env forever
* LH103  ``np.random.*`` / module-level ``random.*`` — host RNG inside
         traced code is a silent constant after the first trace
* LH104  ``.block_until_ready()`` — host sync inside a program
* LH105  ``float()/int()/bool()`` on a parameter — concretizes a tracer
* LH106  ``if``/``while`` on a bare parameter — Python branching on a
         tracer (use ``jnp.where``/``lax.cond``)
"""

from __future__ import annotations

import ast

from .core import Ctx, FileCtx

#: callables whose function argument becomes traced code
_JIT_NAMES = {"jit", "shard_map", "pallas_call"}

_SCOPE_PREFIX = "lighthouse_tpu/"


def _callee_tail(fn) -> str | None:
    """Last attribute/name of a callee: ``jax.jit`` -> ``jit``."""
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _root_names_from_call(node: ast.Call) -> list[str]:
    """Function names traced by a jit-ish call site."""
    if _callee_tail(node.func) not in _JIT_NAMES:
        return []
    out: list[str] = []
    for arg in node.args[:1]:  # the traced callable is always arg 0
        if isinstance(arg, ast.Name):
            out.append(arg.id)
        elif isinstance(arg, ast.Call):
            # jax.jit(_gathered(_verify_core)): the wrapper closes over
            # its Name arguments, which end up traced too
            if (name := _callee_tail(arg.func)) is not None:
                out.append(name)
            out.extend(a.id for a in arg.args if isinstance(a, ast.Name))
    return out


def _is_jit_decorator(dec) -> bool:
    tail = _callee_tail(dec)
    if tail in _JIT_NAMES:
        return True
    # @partial(jax.jit, ...) / @partial(shard_map, mesh=...)
    if (isinstance(dec, ast.Call) and _callee_tail(dec.func) == "partial"
            and dec.args and _callee_tail(dec.args[0]) in _JIT_NAMES):
        return True
    if isinstance(dec, ast.Call):
        return _callee_tail(dec.func) in _JIT_NAMES
    return False


def _collect(f: FileCtx):
    """(name -> FunctionDef table, root function names) for one file."""
    table: dict[str, ast.AST] = {}
    roots: set[str] = set()
    for node in ast.walk(f.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            table[node.name] = node
            if any(_is_jit_decorator(d) for d in node.decorator_list):
                roots.add(node.name)
        elif isinstance(node, ast.Call):
            roots.update(_root_names_from_call(node))
    return table, roots


def _reachable(table: dict[str, ast.AST], roots: set[str]) -> set[str]:
    seen: set[str] = set()
    frontier = [r for r in roots if r in table]
    while frontier:
        name = frontier.pop()
        if name in seen:
            continue
        seen.add(name)
        for node in ast.walk(table[name]):
            if isinstance(node, ast.Call):
                callee = _callee_tail(node.func)
                if callee in table and callee not in seen:
                    frontier.append(callee)
    return seen


_STATIC_ANNOTATIONS = {"int", "float", "bool", "str"}


def _param_names(fn) -> set[str]:
    """Parameters treated as likely tracers. A plain-Python annotation
    (``pad: int``, ``xm1: bool``) documents a STATIC config argument —
    jit marks those static or closes over them — so annotated params
    are exempt from the coercion/branching checks."""
    a = fn.args
    out = set()
    for arg in (a.posonlyargs + a.args + a.kwonlyargs):
        ann = arg.annotation
        if (isinstance(ann, ast.Name)
                and ann.id in _STATIC_ANNOTATIONS):
            continue
        out.add(arg.arg)
    return out


def _bare_param(node, params: set[str]) -> str | None:
    if isinstance(node, ast.Name) and node.id in params:
        return node.id
    if (isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not)):
        return _bare_param(node.operand, params)
    return None


def _check_function(ctx: Ctx, f: FileCtx, fn, via: str) -> None:
    params = _param_names(fn)
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            callee = node.func
            if (isinstance(callee, ast.Attribute)
                    and isinstance(callee.value, ast.Name)):
                mod, attr = callee.value.id, callee.attr
                if mod == "time":
                    ctx.add(
                        f, node.lineno, "LH101",
                        f"time.{attr}() inside jit-traced {fn.name!r} "
                        f"(root: {via}) — trace-time wall clock",
                    )
                elif mod == "os" and attr == "getenv":
                    ctx.add(
                        f, node.lineno, "LH102",
                        f"os.getenv inside jit-traced {fn.name!r} "
                        f"(root: {via}) — env read baked into the trace",
                    )
                elif mod == "random":
                    ctx.add(
                        f, node.lineno, "LH103",
                        f"random.{attr}() inside jit-traced {fn.name!r} "
                        f"(root: {via}) — host RNG becomes a trace "
                        f"constant",
                    )
            if (isinstance(callee, ast.Attribute)
                    and callee.attr == "block_until_ready"):
                ctx.add(
                    f, node.lineno, "LH104",
                    f".block_until_ready() inside jit-traced "
                    f"{fn.name!r} (root: {via}) — host sync in program",
                )
            # np.random.<anything>(...)
            if (isinstance(callee, ast.Attribute)
                    and isinstance(callee.value, ast.Attribute)
                    and callee.value.attr == "random"
                    and isinstance(callee.value.value, ast.Name)
                    and callee.value.value.id in ("np", "numpy")):
                ctx.add(
                    f, node.lineno, "LH103",
                    f"np.random.{callee.attr}() inside jit-traced "
                    f"{fn.name!r} (root: {via})",
                )
            # float(x)/int(x)/bool(x) where x is a parameter
            if (isinstance(callee, ast.Name)
                    and callee.id in ("float", "int", "bool")
                    and len(node.args) == 1):
                p = _bare_param(node.args[0], params)
                if p is not None:
                    ctx.add(
                        f, node.lineno, "LH105",
                        f"{callee.id}({p}) inside jit-traced "
                        f"{fn.name!r} (root: {via}) — concretizes a "
                        f"tracer (ConcretizationTypeError on TPU)",
                    )
        elif isinstance(node, (ast.If, ast.While)):
            p = _bare_param(node.test, params)
            if p is not None:
                kw = "while" if isinstance(node, ast.While) else "if"
                ctx.add(
                    f, node.lineno, "LH106",
                    f"{kw} {p}: inside jit-traced {fn.name!r} "
                    f"(root: {via}) — Python branch on a tracer; use "
                    f"jnp.where/lax.cond",
                )
        # os.environ access anywhere in the body
        elif (isinstance(node, ast.Attribute) and node.attr == "environ"
              and isinstance(node.value, ast.Name)
              and node.value.id == "os"):
            ctx.add(
                f, node.lineno, "LH102",
                f"os.environ access inside jit-traced {fn.name!r} "
                f"(root: {via})",
            )


def run(ctx: Ctx) -> None:
    for f in ctx.files:
        if not (f.rel.startswith(_SCOPE_PREFIX)
                or f.fixture_family == "lh1"):
            continue
        table, roots = _collect(f)
        for name in sorted(_reachable(table, roots)):
            _check_function(ctx, f, table[name], via=name)
