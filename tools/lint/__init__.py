"""lhtpu-lint — AST-based invariant checker for the dispatch matrix,
env-knob registry, and jit-purity.

Run as ``python -m tools.lint`` (``--json`` for machine-readable
findings, ``--changed-only`` for the pre-commit subset,
``--knob-table`` to regenerate the README knob table). Error-code
families:

==========  ==========================================================
LH002       waiver without justification (not itself waivable)
LH1xx       jit-purity (host impurity inside traced code)
LH2xx       env-knob registry coherence
LH3xx       stage/metric-name coherence
LH4xx       program-builder signature contract
LH5xx       resilience hygiene
LH6xx       loadgen determinism
==========  ==========================================================
"""

from .core import Finding, LINT_VERSION, changed_files, run_lint

__all__ = ["Finding", "LINT_VERSION", "changed_files", "run_lint"]
