"""LH4xx — program-builder signature contract.

The dispatch matrix hands the same flat-arg tuple to every program
variant, so a builder whose signature drifts out of canonical order
compiles fine and verifies GARBAGE (args silently transposed). Two
checks pin the contract:

* LH401  a ``_verify_core*`` def (jax_backend) or the inner
         ``body``/``fn`` of a ``build_sharded_*_verifier`` builder does
         not START with the canonical flat-arg prefix (fused variants
         append extra operands after it — only the prefix is pinned)
* LH402  a dispatch-ladder variant has no grouped twin: for every
         non-grouped ``build_sharded_*_verifier`` builder /
         ``_verify*_jit`` program there must be a sibling whose name is
         exactly the same tokens + ``grouped`` (waive for genuinely
         groupless variants)
"""

from __future__ import annotations

import ast

from .core import Ctx, FileCtx

SCOPE = {
    "lighthouse_tpu/jax_backend.py",
    "lighthouse_tpu/parallel/sharding.py",
}

#: jax_backend core verifiers
CORE_PREFIX = ("pk", "pk_inf", "sig", "sig_inf", "msg", "msg_inf",
               "r_bits")
#: sharded plain bodies (split affine planes)
PLAIN_PREFIX = ("pk_x", "pk_y", "pk_inf", "sx", "sy", "sinf",
                "mx", "my", "minf", "r_bits")
#: sharded indexed bodies (pubkey table + gather indices)
INDEXED_PREFIX = ("tx", "ty", "idx", "pk_inf", "sx", "sy", "sinf",
                  "mx", "my", "minf", "r_bits")


def _params(fn) -> tuple[str, ...]:
    a = fn.args
    return tuple(arg.arg for arg in (a.posonlyargs + a.args))


def _check_prefix(ctx: Ctx, f: FileCtx, fn, want: tuple[str, ...],
                  what: str) -> None:
    got = _params(fn)
    if got[:len(want)] != want:
        ctx.add(
            f, fn.lineno, "LH401",
            f"{what} {fn.name!r} breaks the canonical flat-arg order: "
            f"got ({', '.join(got[:len(want)])}), want "
            f"({', '.join(want)}) — the dispatch matrix passes "
            f"positionally",
        )


def _tokens(name: str) -> frozenset[str]:
    return frozenset(t for t in name.split("_") if t)


def _check_twins(ctx: Ctx, f: FileCtx, names: list[tuple[str, int]],
                 what: str) -> None:
    """Every non-grouped variant needs a grouped sibling with the exact
    same token set + ``grouped``."""
    have = {_tokens(n) for n, _ in names}
    for name, lineno in names:
        toks = _tokens(name)
        if "grouped" in toks:
            continue
        if toks | {"grouped"} not in have:
            ctx.add(
                f, lineno, "LH402",
                f"{what} {name!r} has no grouped twin "
                f"({'_'.join(sorted(toks | {'grouped'}))} variant "
                f"missing) — grouped verdicts are a dispatch "
                f"dimension, not an option",
            )


def _check_file(ctx: Ctx, f: FileCtx) -> None:
    builders: list[tuple[str, int]] = []
    programs: list[tuple[str, int]] = []

    for node in f.tree.body:
        if isinstance(node, ast.FunctionDef):
            if (node.name.startswith("_verify_core")):
                _check_prefix(ctx, f, node, CORE_PREFIX,
                              "core verifier")
            elif (node.name.startswith("build_sharded")
                  and node.name.endswith("_verifier")):
                builders.append((node.name, node.lineno))
                want = (INDEXED_PREFIX if "indexed" in _tokens(node.name)
                        else PLAIN_PREFIX)
                for inner in ast.walk(node):
                    if (isinstance(inner, ast.FunctionDef)
                            and inner.name in ("body", "fn")):
                        _check_prefix(ctx, f, inner, want,
                                      "sharded body")
        elif (isinstance(node, ast.Assign) and node.targets
              and isinstance(node.targets[0], ast.Name)):
            name = node.targets[0].id
            if name.startswith("_verify") and name.endswith("_jit"):
                programs.append((name, node.lineno))

    _check_twins(ctx, f, builders, "builder")
    _check_twins(ctx, f, programs, "program")


def run(ctx: Ctx) -> None:
    for f in ctx.files:
        if f.rel in SCOPE or f.fixture_family == "lh4":
            _check_file(ctx, f)
