"""lhtpu-lint core: file loading, waivers, scoping, orchestration.

The linter is pure stdlib-``ast`` — it never imports the code under
analysis (so it runs in milliseconds, needs no JAX, and cannot be
confused by import-time side effects). The one exception is
``lighthouse_tpu/common/knobs.py``, which the knob checks execute in
isolation via importlib (it depends on nothing but the stdlib) so the
knob registry and the generated README table have a single source.

Waiver syntax::

    risky_line()  # lhtpu: ignore[LH502] -- why this swallow is safe

The justification after ``--`` is REQUIRED: a waiver without one is
itself a finding (LH002). Multiple codes: ``ignore[LH201,LH502]``.
"""

from __future__ import annotations

import ast
import os
import re
import subprocess
from dataclasses import dataclass

#: bumped whenever a check family changes behavior; embedded in bench
#: JSON lines (lint provenance) and the --json output.
LINT_VERSION = "1.0.0"

#: directories never walked in full-tree mode
SKIP_DIRS = {
    ".git", "__pycache__", ".pytest_cache", ".jax_cache_tpu",
    ".claude", "build", "dist", "node_modules",
}

#: fixture files deliberately violate the invariants; they are linted
#: only when named explicitly (the golden tests do exactly that).
FIXTURE_DIR = os.path.join("tests", "fixtures", "lint")

_WAIVER_RE = re.compile(
    r"#\s*lhtpu:\s*ignore\[([A-Z0-9_,\s]+)\](\s*--\s*(\S.*))?"
)


@dataclass(frozen=True)
class Finding:
    file: str      # repo-relative path
    line: int      # 1-indexed
    code: str      # e.g. "LH201"
    message: str

    def as_dict(self) -> dict:
        return {
            "file": self.file, "line": self.line,
            "code": self.code, "message": self.message,
        }

    def render(self) -> str:
        return f"{self.file}:{self.line}: {self.code} {self.message}"


class FileCtx:
    """One parsed source file plus its waiver table."""

    def __init__(self, root: str, rel: str, source: str):
        self.root = root
        self.rel = rel.replace(os.sep, "/")
        self.source = source
        self.tree = ast.parse(source, filename=rel)
        # line -> set of waived codes; lines with a waiver but no
        # justification recorded separately (LH002).
        self.waivers: dict[int, set[str]] = {}
        self.unjustified: list[int] = []
        for lineno, text in enumerate(source.splitlines(), start=1):
            m = _WAIVER_RE.search(text)
            if not m:
                continue
            codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
            self.waivers[lineno] = codes
            if not m.group(3):
                self.unjustified.append(lineno)

    @property
    def in_fixture_dir(self) -> bool:
        return self.rel.startswith(FIXTURE_DIR.replace(os.sep, "/"))

    @property
    def fixture_family(self) -> str | None:
        """Golden fixtures opt into exactly ONE family via filename
        (``lh101_pos.py`` -> family ``lh1``) so each file triggers
        exactly one code without tripping sibling families."""
        if not self.in_fixture_dir:
            return None
        m = re.match(r"(lh\d)", os.path.basename(self.rel))
        return m.group(1) if m else None

    def waived(self, line: int, code: str) -> bool:
        codes = self.waivers.get(line)
        return bool(codes) and (code in codes or "ALL" in codes)


class Ctx:
    """Whole-run context handed to every check family."""

    def __init__(self, root: str, files: list[FileCtx],
                 full_tree: bool):
        self.root = root
        self.files = files
        #: True when the whole repo was walked — repo-level checks
        #: (README table staleness, dead knobs, missing grouped twins)
        #: only make sense then, not on a --changed-only subset.
        self.full_tree = full_tree
        self.findings: list[Finding] = []

    def add(self, f: FileCtx, line: int, code: str, message: str) -> None:
        if f.waived(line, code):
            return
        self.findings.append(Finding(f.rel, line, code, message))

    def by_rel(self, rel: str) -> FileCtx | None:
        for f in self.files:
            if f.rel == rel:
                return f
        return None


def iter_python_files(root: str):
    """Repo-relative paths of every lintable .py file (skips fixture
    and vendored/cache dirs)."""
    fixture_prefix = FIXTURE_DIR + os.sep
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames
            if d not in SKIP_DIRS and not d.startswith(".")
        )
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            rel = os.path.relpath(os.path.join(dirpath, name), root)
            if rel.startswith(fixture_prefix):
                continue
            yield rel


def changed_files(root: str) -> list[str]:
    """Repo-relative .py paths from ``git diff --name-only HEAD`` plus
    untracked files — the quick pre-commit scope."""
    out: list[str] = []
    for args in (
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        proc = subprocess.run(
            args, cwd=root, capture_output=True, text=True, check=False,
        )
        out.extend(
            line.strip() for line in proc.stdout.splitlines()
            if line.strip().endswith(".py")
        )
    seen: set[str] = set()
    return [p for p in out if not (p in seen or seen.add(p))]


def _load(root: str, rel: str) -> FileCtx | None:
    path = os.path.join(root, rel)
    try:
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
        return FileCtx(root, rel, source)
    except (OSError, SyntaxError, UnicodeDecodeError):
        return None


def run_lint(root: str, files: list[str] | None = None) -> list[Finding]:
    """Lint the tree (or an explicit repo-relative file list) and
    return all findings, sorted by (file, line, code).

    Full-tree mode additionally runs the repo-level checks (README
    knob-table staleness, dead knobs, grouped-twin completeness).
    Explicit fixture files under ``tests/fixtures/lint/`` are placed in
    every family's scope so one tiny file can exercise one code.
    """
    from . import (builder_checks, determinism_checks, hygiene_checks,
                   knobs_checks, purity_checks, stage_checks)

    root = os.path.abspath(root)
    full_tree = files is None
    rels = list(iter_python_files(root)) if full_tree else [
        f.replace(os.sep, "/") for f in files
    ]
    ctxs = [c for c in (_load(root, rel) for rel in rels) if c is not None]
    ctx = Ctx(root, ctxs, full_tree)

    for f in ctxs:
        for line in f.unjustified:
            # not waivable: a waiver of the waiver-hygiene check would
            # defeat the justification requirement
            ctx.findings.append(Finding(
                f.rel, line, "LH002",
                "waiver missing justification (want "
                "'# lhtpu: ignore[CODE] -- why')",
            ))

    purity_checks.run(ctx)        # LH1xx
    knobs_checks.run(ctx)         # LH2xx
    stage_checks.run(ctx)         # LH3xx
    builder_checks.run(ctx)       # LH4xx
    hygiene_checks.run(ctx)       # LH5xx
    determinism_checks.run(ctx)   # LH6xx

    # identical findings can be emitted twice (e.g. a nested traced fn
    # reachable through two paths) — Finding is frozen, so dedupe by id
    return sorted(
        set(ctx.findings), key=lambda fi: (fi.file, fi.line, fi.code)
    )
