"""LH6xx — loadgen determinism.

The traffic generator's whole contract is "same seed, same mainnet
slice, same digests" (soak compares epoch digests across runs; CI
compares them across versions). One unseeded RNG or wall-clock read in
the generation path quietly breaks replayability:

* LH601  unseeded randomness — module-level ``random.*`` calls,
         ``random.Random()`` with no seed, legacy ``np.random.*``
         globals, ``np.random.default_rng()`` with no seed
* LH602  wall-clock read — ``time.time()``, ``datetime.now()`` and
         friends. ``time.monotonic``/``perf_counter`` stay legal: they
         measure duration, they don't enter digests.
"""

from __future__ import annotations

import ast

from .core import Ctx, FileCtx

_SCOPE_PREFIX = "lighthouse_tpu/loadgen/"

_WALL_CLOCK_TIME = {"time", "ctime", "localtime", "gmtime", "strftime"}
_WALL_CLOCK_DT = {"now", "utcnow", "today"}


def _check_file(ctx: Ctx, f: FileCtx) -> None:
    for node in ast.walk(f.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            continue
        base = fn.value
        # random.<fn>() on the module — the hidden global Mersenne
        # Twister; random.Random(seed) is the blessed form
        if isinstance(base, ast.Name) and base.id == "random":
            if fn.attr == "Random":
                if not node.args and not node.keywords:
                    ctx.add(
                        f, node.lineno, "LH601",
                        "random.Random() without a seed — loadgen "
                        "must replay from cfg.seed",
                    )
            elif fn.attr[:1].islower():
                ctx.add(
                    f, node.lineno, "LH601",
                    f"module-level random.{fn.attr}() uses the hidden "
                    f"global RNG — thread a random.Random(seed)",
                )
        # np.random.<fn>() — legacy global, or unseeded default_rng()
        elif (isinstance(base, ast.Attribute) and base.attr == "random"
              and isinstance(base.value, ast.Name)
              and base.value.id in ("np", "numpy")):
            if fn.attr == "default_rng":
                if not node.args and not node.keywords:
                    ctx.add(
                        f, node.lineno, "LH601",
                        "np.random.default_rng() without a seed",
                    )
            else:
                ctx.add(
                    f, node.lineno, "LH601",
                    f"legacy np.random.{fn.attr}() global RNG — use a "
                    f"seeded Generator",
                )
        # time.time() and friends
        elif (isinstance(base, ast.Name) and base.id == "time"
              and fn.attr in _WALL_CLOCK_TIME):
            ctx.add(
                f, node.lineno, "LH602",
                f"wall-clock time.{fn.attr}() in loadgen — use "
                f"time.monotonic()/perf_counter() (durations) or a "
                f"seeded virtual clock (digests)",
            )
        # datetime.now()/utcnow()/today()
        elif (fn.attr in _WALL_CLOCK_DT
              and isinstance(base, (ast.Name, ast.Attribute))
              and (base.id if isinstance(base, ast.Name)
                   else base.attr) in ("datetime", "date")):
            ctx.add(
                f, node.lineno, "LH602",
                f"wall-clock datetime {fn.attr}() in loadgen",
            )


def run(ctx: Ctx) -> None:
    for f in ctx.files:
        if (f.rel.startswith(_SCOPE_PREFIX)
                or f.fixture_family == "lh6"):
            _check_file(ctx, f)
