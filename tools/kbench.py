"""Micro-benchmark individual fused kernels on the current device.

Usage: python tools/kbench.py [--fresh] [S] [name ...]

Names: scalar_g1 scalar_g2 subgroup subgroup_full to_affine_g1
       to_affine_g2 miller sswu sswu_iso cofactor psi_subgroup
       map_resident mont_mul_dense fp2_mul line_eval final_exp

Each kernel is compiled (persistent cache), warmed, then timed over
REPS=5 with block_until_ready. Inputs are generator-point lanes — timing
is data-independent (constant-time chains).

``--fresh`` runs each requested row in its OWN subprocess: one cold
python → jax → kernel lifecycle per row, so a number can never ride a
stale device sync or a warm tunnel left by an earlier kernel (the
stale-sync hazard documented in README). Default rows under --fresh are
the ISSUE 10 hash-side trio (sswu_iso, cofactor, psi_subgroup) whose
MXU-ladder/resident wins must be confirmed per-kernel from cold."""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "")

import jax
import jax.numpy as jnp

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache_tpu"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)

from lighthouse_tpu.jax_backend import _rand_bits_array
from lighthouse_tpu.ops import tkernel as tk
from lighthouse_tpu.ops import tkernel_calls as tc
from lighthouse_tpu.ops.points import G1_GEN_DEV, G2_GEN_DEV

REPS = int(os.environ.get("KBENCH_REPS", "5"))


def timeit(label, fn):
    t0 = time.perf_counter()
    out = fn()
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(REPS):
        jax.block_until_ready(fn())
    dt = (time.perf_counter() - t0) / REPS * 1e3
    print(f"{label:28s} {dt:9.1f} ms   (first call {compile_s:.1f}s)")
    sys.stdout.flush()


#: default rows for --fresh: the hash-side kernels whose ISSUE 10 wins
#: are claimed per-kernel (cold process each, no shared device state) —
#: including map_resident, whose PR-10 claim previously had no cold row —
#: plus the carry-chain trio (mont_mul_dense, fp2_mul, line_eval) that
#: measures the LHTPU_LAZY_REDUCE / LHTPU_MXU_CARRY bar per-kernel.
FRESH_NAMES = (
    "sswu_iso", "cofactor", "psi_subgroup", "map_resident",
    "mont_mul_dense", "fp2_mul", "line_eval",
)


def run_fresh(S: int, names) -> int:
    """One subprocess per row: python -> jax init -> single kernel.

    The child is this same script with one name; its stdout rows are
    re-emitted under a ``fresh`` prefix so a sweep reads as one table.
    Returns the count of failed children (nonzero exit / no row)."""
    import subprocess

    failed = 0
    for name in names:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), str(S), name],
            capture_output=True, text=True,
        )
        rows = [
            ln for ln in proc.stdout.splitlines()
            if ln and not ln.startswith("device=")
        ]
        if proc.returncode != 0 or not rows:
            failed += 1
            tail = (proc.stderr or proc.stdout).strip().splitlines()[-3:]
            print(f"fresh {name:22s} FAILED rc={proc.returncode} "
                  f"{' | '.join(tail)}")
        else:
            for ln in rows:
                print(f"fresh {ln}")
        sys.stdout.flush()
    return failed


def main():
    argv = [a for a in sys.argv[1:] if a != "--fresh"]
    fresh = len(argv) != len(sys.argv) - 1
    S = int(argv[0]) if argv else 2048
    if fresh:
        names = argv[1:] or list(FRESH_NAMES)
        print(f"device=fresh-subprocess S={S} reps={REPS}")
        sys.exit(1 if run_fresh(S, names) else 0)
    names = argv[1:] or [
        "scalar_g1", "scalar_g2", "subgroup", "to_affine_g1",
        "to_affine_g2", "miller", "sswu", "cofactor", "final_exp",
    ]
    print(f"device={jax.devices()[0].platform} S={S} reps={REPS}")

    g1x = jnp.broadcast_to(jnp.asarray(G1_GEN_DEV[0])[:, None], (48, S))
    g1y = jnp.broadcast_to(jnp.asarray(G1_GEN_DEV[1])[:, None], (48, S))
    g2x = jnp.broadcast_to(jnp.asarray(G2_GEN_DEV[0])[..., None], (2, 48, S))
    g2y = jnp.broadcast_to(jnp.asarray(G2_GEN_DEV[1])[..., None], (2, 48, S))
    inf_row = jnp.zeros((1, S), jnp.int32)
    bits_t = jnp.transpose(jnp.asarray(_rand_bits_array(S)))
    jax.block_until_ready((g1x, g1y, g2x, g2y, bits_t))

    jac1 = (g1x, g1y, jnp.broadcast_to(tk._c("R"), (48, S)))
    jac2 = (g2x, g2y, jnp.broadcast_to(
        jnp.concatenate([tk._c("R")[None], jnp.zeros((1, 48, 1), jnp.int32)]),
        (2, 48, S)))

    for name in names:
        if name == "scalar_g1":
            timeit("scalar_mul_g1", lambda: tc.scalar_mul_g1_t(g1x, g1y, inf_row, bits_t))
        elif name == "scalar_g2":
            timeit("scalar_mul_g2", lambda: tc.scalar_mul_g2_t(g2x, g2y, inf_row, bits_t))
        elif name == "subgroup":
            timeit("subgroup_fast (psi)", lambda: tc.subgroup_check_g2_fast_t(g2x, g2y, inf_row))
        elif name == "subgroup_full":
            timeit("subgroup_full ([r]Q)", lambda: tc.subgroup_check_g2_t(g2x, g2y, inf_row))
        elif name == "to_affine_g1":
            timeit("to_affine_g1", lambda: tc.to_affine_g1_t(jac1))
        elif name == "to_affine_g2":
            timeit("to_affine_g2", lambda: tc.to_affine_g2_t(jac2))
        elif name == "miller":
            timeit("miller_loop", lambda: tc.miller_loop_kernel_t(
                (g1x, g1y), inf_row[0] != 0, (g2x, g2y), inf_row[0] != 0))
        elif name in ("sswu", "sswu_iso"):
            from lighthouse_tpu.ops.tkernel_htc import _interpret, _sswu_iso_t
            u = g2x  # any Fp2 lanes work as field input
            timeit("sswu+iso", lambda: _sswu_iso_t(u, _interpret()))
        elif name == "cofactor":
            from lighthouse_tpu.ops.tkernel_htc import _cofactor_t, _interpret
            timeit("cofactor", lambda: _cofactor_t(jac2, _interpret()))
        elif name == "psi_subgroup":
            # same kernel as "subgroup"; named row so the ISSUE 10
            # ladder-stacking win reads per-kernel in fresh sweeps
            timeit("psi_subgroup", lambda: tc.subgroup_check_g2_fast_t(
                g2x, g2y, inf_row))
        elif name == "map_resident":
            from lighthouse_tpu.ops.tkernel_htc import (
                _interpret,
                _map_to_g2_resident_t,
            )
            us = jnp.broadcast_to(
                jnp.asarray(G2_GEN_DEV[0])[None, ..., None], (2, 2, 48, S)
            )
            timeit("map_resident (sswu..cof)", lambda:
                   _map_to_g2_resident_t(us, _interpret()))
        elif name == "mont_mul_dense":
            # dependent chain so the carry path is on the critical path,
            # not hidden behind the conv's MXU throughput
            @jax.jit
            def _mm16(x, y):
                for _ in range(16):
                    x = tk.mont_mul_t(x, y)
                return x
            timeit("mont_mul_dense (x16)", lambda: _mm16(g1x, g1y))
        elif name == "fp2_mul":
            @jax.jit
            def _fp2x8(x, y):
                for _ in range(8):
                    x = tk.fp2_mul_t(x, y)
                return x
            timeit("fp2_mul (x8)", lambda: _fp2x8(g2x, g2y))
        elif name == "line_eval":
            # one Miller-loop body iteration: doubling step + sparse
            # f*line product, lazy/strict chosen by knob at trace time
            from lighthouse_tpu.ops import tkernel_pairing as tp

            @jax.jit
            def _line(f, X, Y, Z, xp, yp):
                if tk._lazy_enabled():
                    T2, line_w = tp._dbl_step_lazy((X, Y, Z))
                    return tp._mul_line_sparse_lazy(f, line_w, xp, yp)
                T2, line = tp._dbl_step((X, Y, Z))
                return tp._mul_line_sparse(f, line, xp, yp)

            f12 = tp.fp12_one_t(g1x)
            timeit("line_eval (dbl+sparse)", lambda: _line(
                f12, jac2[0], jac2[1], jac2[2], g1x, g1y))
        elif name == "final_exp":
            f = jnp.broadcast_to(
                jnp.zeros((2, 3, 2, 48, 1), jnp.int32).at[0, 0, 0].set(tk._c("R")),
                (2, 3, 2, 48, min(S, 128)),
            )
            timeit("final_exp", lambda: tc.final_exp_kernel_t(f))
        else:
            print(f"unknown kernel: {name}")


if __name__ == "__main__":
    main()
