"""Stage-level wall-time profile of the fused verifier + hash path on TPU.

Times each device stage of jax_backend._verify_core_fused and the
hash-to-G2 pipeline separately (block_until_ready around each), plus the
host-side assembly costs, at the bench shape S=2048, K=1. Guides kernel
optimization: run after kernel changes to see which stage moved.

With ``--json`` the human-readable lines go to stderr and stdout gets
ONE parseable JSON line — {"metric": "bls_stage_profile", "stages_ms":
{...}} — the same per-stage breakdown shape bench.py embeds, so a
round's BENCH json can carry a device-stage profile.

``--devices N`` switches to the multi-chip profile (ISSUE 8): one warm
sharded verify on an N-way mesh (forced host devices off-TPU), per-shard
stage attribution, and the cross-chip fold round measured in isolation
with its share of the device dispatch stage.

Usage:  python tools/profile_stages.py [S] [--json] [--devices N]
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

JSON_MODE = "--json" in sys.argv

#: label -> milliseconds, accumulated by record()/timeit for --json
STAGES_MS: dict[str, float] = {}


def record(label: str, ms: float) -> None:
    STAGES_MS[label] = round(ms, 3)
    print(f"{label:42s} {ms:10.1f} ms",
          file=sys.stderr if JSON_MODE else sys.stdout)

def _devices_arg() -> int | None:
    """``--devices N`` — profile the SHARDED dispatch at an N-way mesh
    instead of the single-chip kernel stages; None when absent."""
    if "--devices" not in sys.argv:
        return None
    i = sys.argv.index("--devices")
    if i + 1 < len(sys.argv):
        try:
            return max(1, int(sys.argv[i + 1]))
        except ValueError:
            pass
    return 8


DEVICES = _devices_arg()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "")

# The host mesh must be forced BEFORE jax initializes (XLA reads the
# flag once, at backend init); only affects the CPU platform.
if DEVICES and DEVICES > 1:
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + f" --xla_force_host_platform_device_count={DEVICES}"
        ).strip()

import jax
import jax.numpy as jnp

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache_tpu"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)

from lighthouse_tpu.crypto.bls.api import SecretKey, SignatureSet
from lighthouse_tpu.crypto.bls.hash_to_curve import hash_to_g2
from lighthouse_tpu.jax_backend import _rand_bits_array
from lighthouse_tpu.ops import tkernel as tk
from lighthouse_tpu.ops import tkernel_calls as tc
from lighthouse_tpu.ops.points import (
    FP2_OPS, FP_OPS, g1_to_dev, g2_to_dev, pt_from_affine, pt_tree_sum,
    pt_tree_sum_axis,
)
from lighthouse_tpu.ops.pairing import fp12_tree_prod
from lighthouse_tpu.utils import next_pow2


def timeit(label, fn, reps=3):
    jax.block_until_ready(fn())  # warm / compile, synchronized
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
        jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps * 1e3
    record(label, dt)
    return dt


def main():
    args = [a for a in sys.argv[1:] if not a.startswith("-")]
    S = int(args[0]) if args else 2048
    K = 1
    print(f"device={jax.devices()[0].platform} S={S} K={K}",
          file=sys.stderr if JSON_MODE else sys.stdout)

    sks = [SecretKey.from_int(i + 101) for i in range(S)]
    msgs = [i.to_bytes(32, "big") for i in range(S)]
    sets = [
        SignatureSet.single_pubkey(sk.sign(m), sk.public_key(), m)
        for sk, m in zip(sks, msgs)
    ]

    # ------------------------------------------------ host assembly costs
    t0 = time.perf_counter()
    px, py, pinf = g1_to_dev([s.signing_keys[0].point for s in sets])
    record('host g1_to_dev (pubkeys)', (time.perf_counter()-t0)*1e3)
    px, py, pinf = px.reshape(S, K, 48), py.reshape(S, K, 48), pinf.reshape(S, K)
    t0 = time.perf_counter()
    sx, sy, sinf = g2_to_dev([s.signature.point for s in sets])
    record('host g2_to_dev (sigs)', (time.perf_counter()-t0)*1e3)
    t0 = time.perf_counter()
    mpts = [hash_to_g2(m) for m in msgs]
    record('host hash_to_g2 python x S', (time.perf_counter()-t0)*1e3)
    mx, my, minf = g2_to_dev(mpts)
    t0 = time.perf_counter()
    r_bits = _rand_bits_array(S)
    record('host rand bits', (time.perf_counter()-t0)*1e3)

    pk = (jnp.asarray(px), jnp.asarray(py))
    pinf_d = jnp.asarray(pinf)
    sig = (jnp.asarray(sx), jnp.asarray(sy))
    sinf_d = jnp.asarray(sinf)
    msg = (jnp.asarray(mx), jnp.asarray(my))
    minf_d = jnp.asarray(minf)
    bits = jnp.asarray(r_bits)
    jax.block_until_ready((pk, sig, msg, bits))

    # ------------------------------------------------ device stage timings
    # pk aggregation tree (K=1: near no-op) + to-affine
    pk_j = pt_from_affine(FP_OPS, pk[0], pk[1], pinf_d)
    agg = pt_tree_sum_axis(FP_OPS, pk_j, axis=1, axis_size=K)
    agg = jax.block_until_ready(agg)
    agg_t = tuple(tk.batch_to_t(c) for c in agg)
    agg_t = jax.block_until_ready(agg_t)

    timeit("to_affine_g1 (agg)", lambda: tc.to_affine_g1_t(agg_t))
    ax, ay, ainf = tc.to_affine_g1_t(agg_t)
    ainf_row = ainf[None, :].astype(jnp.int32)
    bits_t = jnp.transpose(bits)
    sig_t = (tk.batch_to_t(sig[0]), tk.batch_to_t(sig[1]))
    sig_t = jax.block_until_ready(sig_t)
    sinf_row = sinf_d[None, :].astype(jnp.int32)

    timeit("scalar_mul_g1 (RLC pk)", lambda: tc.scalar_mul_g1_t(ax, ay, ainf_row, bits_t))
    rpk = jax.block_until_ready(tc.scalar_mul_g1_t(ax, ay, ainf_row, bits_t))
    timeit("scalar_mul_g2 (RLC sig)", lambda: tc.scalar_mul_g2_t(sig_t[0], sig_t[1], sinf_row, bits_t))
    rsig = jax.block_until_ready(tc.scalar_mul_g2_t(sig_t[0], sig_t[1], sinf_row, bits_t))
    timeit("subgroup_check_g2_fast", lambda: tc.subgroup_check_g2_fast_t(sig_t[0], sig_t[1], sinf_row))

    rsig_c = tuple(tk.batch_from_t(c) for c in rsig)
    timeit("pt_tree_sum rsig (XLA glue)", lambda: pt_tree_sum(FP2_OPS, rsig_c, S))
    sig_acc = jax.block_until_ready(pt_tree_sum(FP2_OPS, rsig_c, S))
    sig_acc_t = tuple(tk.batch_to_t(c[None]) for c in sig_acc)
    timeit("to_affine_g2 (sig acc, 1 lane)", lambda: tc.to_affine_g2_t(sig_acc_t))
    timeit("to_affine_g1 (rpk)", lambda: tc.to_affine_g1_t(rpk))

    rx, ry, rinf = jax.block_until_ready(tc.to_affine_g1_t(rpk))
    sax, say, sainf = jax.block_until_ready(tc.to_affine_g2_t(sig_acc_t))
    from lighthouse_tpu.ops.limb import neg as limb_neg
    from lighthouse_tpu.ops.points import G1_GEN_DEV
    neg_g1 = (G1_GEN_DEV[0][:, None], limb_neg(G1_GEN_DEV[1])[:, None])
    g1_x = jnp.concatenate([rx, neg_g1[0]], axis=-1)
    g1_y = jnp.concatenate([ry, neg_g1[1]], axis=-1)
    g1_inf = jnp.concatenate([rinf, jnp.zeros((1,), bool)])
    msg_t = (tk.batch_to_t(msg[0]), tk.batch_to_t(msg[1]))
    g2_x = jnp.concatenate([msg_t[0], sax], axis=-1)
    g2_y = jnp.concatenate([msg_t[1], say], axis=-1)
    g2_inf = jnp.concatenate([minf_d, sainf])
    args = jax.block_until_ready((g1_x, g1_y, g1_inf, g2_x, g2_y, g2_inf))

    timeit("miller_loop kernel (S+1 lanes)",
           lambda: tc.miller_loop_kernel_t((g1_x, g1_y), g1_inf, (g2_x, g2_y), g2_inf))
    f = jax.block_until_ready(
        tc.miller_loop_kernel_t((g1_x, g1_y), g1_inf, (g2_x, g2_y), g2_inf))

    from lighthouse_tpu.ops import tower
    M = next_pow2(S + 1)
    f_c = tk.batch_from_t(f)
    pad = M - (S + 1)
    ones = jnp.broadcast_to(tower.FP12_ONE, (pad, *tower.FP12_ONE.shape))
    f_cp = jax.block_until_ready(jnp.concatenate([f_c, ones]))
    timeit("fp12_tree_prod (XLA glue)", lambda: fp12_tree_prod(f_cp, M))
    f1 = jax.block_until_ready(fp12_tree_prod(f_cp, M))
    fe1 = timeit("final_exp kernel (1 lane)",
                 lambda: tc.final_exp_kernel_t(tk.batch_to_t(f1[None])))

    # Grouped-verdict overhead (ISSUE 5): poison triage folds the Miller
    # product per group, so the final exponentiation runs [G]-batched
    # instead of on one collapsed lane. The delta between these two rows
    # is the clean-batch price of carrying G verdicts per dispatch.
    from lighthouse_tpu.jax_backend import _verdict_groups
    G = _verdict_groups() or 32
    fG = jax.block_until_ready(jnp.broadcast_to(f1[None], (G, *f1.shape)))
    feG = timeit(f"final_exp kernel ({G} verdict lanes)",
                 lambda: tc.final_exp_kernel_t(tk.batch_to_t(fG)))
    record("grouped_verdict_final_exp_overhead", feG - fe1)

    # ------------------------------------------------ hash path stages
    from lighthouse_tpu.ops.htc import DST, hash_to_field_dev
    from lighthouse_tpu.ops.tkernel_htc import (
        _cofactor_t, _interpret, _map_to_g2_fused, _map_to_g2_resident_t,
        _sswu_iso_t,
    )

    t0 = time.perf_counter()
    u = jnp.asarray(hash_to_field_dev(msgs, DST))
    u = jax.block_until_ready(u)
    record('host hash_to_field (SHA)', (time.perf_counter()-t0)*1e3)

    # chained A/B path (LHTPU_HTC_RESIDENT=0): per-kernel attribution
    n = u.shape[0]
    flat = jnp.moveaxis(u, 1, 0).reshape(2 * n, 2, 48)
    ut = jax.block_until_ready(tk.batch_to_t(flat))
    timeit("sswu+iso kernel (2S lanes)", lambda: _sswu_iso_t(ut, _interpret()))
    X, Y, Z = jax.block_until_ready(_sswu_iso_t(ut, _interpret()))
    F2 = tk.fp2_ops_t()
    from lighthouse_tpu.ops.points import pt_add
    Q = jax.block_until_ready(pt_add(
        F2, (X[..., :n], Y[..., :n], Z[..., :n]),
        (X[..., n:], Y[..., n:], Z[..., n:])))
    timeit("cofactor kernel (S lanes)", lambda: _cofactor_t(Q, _interpret()))
    Qc = jax.block_until_ready(_cofactor_t(Q, _interpret()))
    timeit("to_affine_g2 (hash out)", lambda: tc.to_affine_g2_t(Qc))
    # resident program (ISSUE 10 tentpole b): same math, one pallas_call
    us = jax.block_until_ready(jnp.moveaxis(u, 0, -1))
    timeit("map_resident (sswu..cof fused)",
           lambda: _map_to_g2_resident_t(us, _interpret()))
    timeit("hash full _map_to_g2_fused", lambda: _map_to_g2_fused(u))

    # ------------------------------------------- dedup sub-stage profile
    # The backend's htc_dedup/htc_map/htc_cofactor split (detail.stages)
    # under protocol-shaped duplication: S rows collapsing to S/dup
    # distinct messages. dup=1 is the worst case (no sharing); dup=64 is
    # the mainnet committee shape (ISSUE 10 tentpole c).
    from lighthouse_tpu import blsrt
    from lighthouse_tpu.jax_backend import JaxBackend
    from lighthouse_tpu.crypto.bls.curve import g2_infinity

    be = JaxBackend()
    inf2 = g2_infinity()
    for dup in (1, 64):
        dmsgs = [
            (i // dup).to_bytes(32, "big") for i in range(S)
        ]
        sub: dict[str, float] = {}
        blsrt.reset_input_caches()
        be._hash_message_bytes(dmsgs, S, inf2, stages=sub)  # warm/compile
        sub.clear()
        blsrt.reset_input_caches()
        t0 = time.perf_counter()
        out = be._hash_message_bytes(dmsgs, S, inf2, stages=sub)
        jax.block_until_ready(out)
        total = (time.perf_counter() - t0) * 1e3
        for stage in ("htc_dedup", "htc_map", "htc_cofactor"):
            record(f"{stage} (dup={dup})", sub.get(stage, 0.0) * 1e3)
        record(f"hash_message_bytes e2e (dup={dup})", total)

    # ------------------------------------------- counted-op model rows
    # Per-set instance counts of the pairing hot bodies (ISSUE 18): the
    # stage-level evidence that a knob moved the CARRY/MAC mix, not just
    # the headline ms. Abstract traces only — no compiles.
    op_model = counted_op_model()
    for cfg, counts in op_model["configs"].items():
        print(f"op_model[{cfg}]  " + "  ".join(
            f"{k}={v}" for k, v in sorted(counts.items())),
            file=sys.stderr if JSON_MODE else sys.stdout)

    # ------------------------------------------- pipelined overlap report
    # One end-to-end verify through the pipelined microbatch engine
    # (common/pipeline.py): per host stage, how many seconds ran hidden
    # behind device compute vs exposed in front of it. Skipped when the
    # pipeline is disabled or S is below LHTPU_PIPELINE_MIN_SETS.
    overlap = profile_pipeline_overlap(sets)

    if JSON_MODE:
        print(json.dumps({
            "metric": "bls_stage_profile",
            "stages_ms": STAGES_MS,
            "detail": {"S": S, "K": K,
                       "device": jax.devices()[0].platform,
                       "verdict_groups": G,
                       "overlap": overlap,
                       "stages": {"op_model": op_model}},
        }), flush=True)


def counted_op_model() -> dict:
    """Per-set counted-op model of the PAIRING hot path (ISSUE 18).

    Counts op INSTANCES (a stacked call-site = 1, matching the README
    roofline methodology) by abstractly tracing the Miller-loop bodies
    — doubling step + sparse f*line product, and the mixed-add body —
    with tkernel's trace-time counters under each knob configuration,
    then extrapolates with the static schedule (63 dbl + 5 dbl_add
    bodies for the BLS12-381 |x|). jax.eval_shape only: no XLA compile,
    so this costs trace time (~seconds), not compile minutes.

    Emitted metrics per config: schoolbook (VPU) MACs, serial / KS /
    MXU carry-chain instances, lazy w_norm passes, MXU MACs. Stages the
    knobs do not touch (ladders, sswu, host) are unchanged by
    construction and omitted — compare configs row-to-row."""
    from lighthouse_tpu.crypto.bls.constants import X as _BLS_X
    from lighthouse_tpu.ops import tkernel_pairing as tp

    n_dbl = abs(_BLS_X).bit_length() - 1
    n_add = bin(abs(_BLS_X)).count("1") - 1

    fp = jax.ShapeDtypeStruct((48, 1), jnp.int32)
    fp2 = jax.ShapeDtypeStruct((2, 48, 1), jnp.int32)
    f12 = jax.ShapeDtypeStruct((2, 3, 2, 48, 1), jnp.int32)

    # bodies are (re)defined per config: jax.eval_shape caches traces on
    # (function identity, avals), and a cache hit skips the Python trace
    # the counters live in — a stale closure would count zero
    def make_bodies():
        def dbl_body(f, X, Y, Z, xp, yp):
            if tk._lazy_enabled():
                T2, line_w = tp._dbl_step_lazy((X, Y, Z))
                return tp._mul_line_sparse_lazy(f, line_w, xp, yp), T2
            T2, line = tp._dbl_step((X, Y, Z))
            return tp._mul_line_sparse(f, line, xp, yp), T2

        def add_body(f, X, Y, Z, xq, yq, xp, yp):
            if tk._lazy_enabled():
                Ta, line_w = tp._add_step_lazy((X, Y, Z), (xq, yq))
                return tp._mul_line_sparse_lazy(f, line_w, xp, yp), Ta
            Ta, line = tp._add_step((X, Y, Z), (xq, yq))
            return tp._mul_line_sparse(f, line, xp, yp), Ta

        return dbl_body, add_body

    def trace_counts(fn, *argspecs):
        with tk.count_ops() as counts:
            jax.eval_shape(fn, *argspecs)
        return counts

    configs = {
        "strict": {},
        "lazy": {"LHTPU_LAZY_REDUCE": "1"},
        "mxu_carry": {"LHTPU_MXU_CARRY": "1"},
        "lazy+mxu_carry": {"LHTPU_LAZY_REDUCE": "1",
                           "LHTPU_MXU_CARRY": "1"},
    }
    knob_names = ("LHTPU_LAZY_REDUCE", "LHTPU_MXU_CARRY")
    saved = {k: os.environ.get(k) for k in knob_names}
    out: dict[str, dict[str, int]] = {}
    try:
        for name, env in configs.items():
            for k in knob_names:
                os.environ.pop(k, None)
            os.environ.update(env)
            dbl_body, add_body = make_bodies()
            dbl = trace_counts(dbl_body, f12, fp2, fp2, fp2, fp, fp)
            add = trace_counts(
                add_body, f12, fp2, fp2, fp2, fp2, fp2, fp, fp)
            total = {
                k: n_dbl * dbl.get(k, 0) + n_add * add.get(k, 0)
                for k in set(dbl) | set(add)
            }
            out[name] = {
                "schoolbook_macs": total.get("conv_mac", 0)
                + total.get("fold_vpu_mac", 0),
                "carry_serial": total.get("carry_serial", 0),
                "carry_ks": total.get("carry_ks", 0),
                "carry_mxu": total.get("carry_mxu", 0),
                "w_norm_passes": total.get("w_norm", 0),
                "mont_products": total.get("mont_product", 0),
                "mxu_macs": total.get("mxu_mac", 0),
            }
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return {
        "unit": "per pairing lane (63 dbl + 5 dbl_add bodies)",
        "configs": out,
    }


def profile_multichip(n_dev: int) -> None:
    """``--devices N`` (ISSUE 8): stage attribution of a SHARDED verify.

    Runs a warm end-to-end verify with the dispatch engine forced onto
    an N-way mesh and reports the host stages (pack / hash / scalars)
    that stay serial, the device dispatch stage that now runs with
    S/N sets per shard, and — separately measured via the engine's fold
    probe — the cross-chip fold round that is the sharding overhead,
    as a share of the device stage."""
    from lighthouse_tpu import jax_backend as jb
    from lighthouse_tpu.crypto.bls.api import SecretKey, SignatureSet
    from lighthouse_tpu.parallel import engine

    out = sys.stderr if JSON_MODE else sys.stdout
    tpu = jax.devices()[0].platform == "tpu"
    if not tpu:
        # reuse the test tier's cache — the sharded classic programs at
        # the (S=8, K=1) profile shape are exactly what it compiles
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), ".jax_cache"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

    S = int(os.environ.get("PROFILE_SETS", "2048" if tpu else "8"))
    print(f"device={jax.devices()[0].platform} devices={n_dev} S={S} "
          f"(multichip profile)", file=out)

    sks = [SecretKey.from_int(i + 7) for i in range(S)]
    msgs = [bytes([(i % 255) + 1]) * 32 for i in range(S)]
    sets = [
        SignatureSet.single_pubkey(sks[i].sign(msgs[i]),
                                   sks[i].public_key(), msgs[i])
        for i in range(S)
    ]

    saved = {
        k: os.environ.get(k)
        for k in ("LHTPU_DEVICES", "LHTPU_SHARDED_VERIFY", "LHTPU_PIPELINE")
    }
    os.environ["LHTPU_DEVICES"] = str(n_dev)
    os.environ["LHTPU_SHARDED_VERIFY"] = "1" if n_dev > 1 else "0"
    os.environ["LHTPU_PIPELINE"] = "0"
    try:
        be = jb.JaxBackend()
        assert be.verify_signature_sets(sets)   # compile / cache load
        t0 = time.perf_counter()
        assert be.verify_signature_sets(sets)   # steady state
        wall = time.perf_counter() - t0

        rep = jb.dispatch_stage_report()
        par = rep.get("parallel") or {}
        stages_ms = rep.get("stages_ms") or {}
        per_shard = par.get("sets_per_chip")
        for stage, ms in sorted(stages_ms.items()):
            suffix = (f"  ({per_shard} sets/shard x {n_dev})"
                      if stage == "dispatch" and n_dev > 1 else "")
            record(f"{stage}{suffix}", ms)
        record("e2e (warm)", wall * 1e3)

        fold_ms = engine.measure_fold_ms(n_dev) if n_dev > 1 else 0.0
        dispatch_ms = stages_ms.get("dispatch") or 0.0
        fold_share = (round(fold_ms / dispatch_ms, 4)
                      if dispatch_ms > 0 else 0.0)
        record("cross_chip_fold (probe)", fold_ms)
        print(f"multichip: path={rep.get('path')} "
              f"mesh={par.get('mesh')} pad_waste={par.get('pad_waste')} "
              f"fold_share_of_dispatch={fold_share}", file=out)

        if JSON_MODE:
            print(json.dumps({
                "metric": "bls_stage_profile_multichip",
                "stages_ms": STAGES_MS,
                "detail": {
                    "S": S,
                    "device": jax.devices()[0].platform,
                    "devices": n_dev,
                    "sets_per_shard": per_shard,
                    "fold_ms": fold_ms,
                    "fold_share": fold_share,
                    "path": rep.get("path"),
                    "parallel": par,
                },
            }), flush=True)
    finally:
        for key, val in saved.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val


def profile_pipeline_overlap(sets) -> dict:
    """Run one pipelined verify and report host-hidden vs host-exposed
    seconds per dispatch stage (None-shaped dict when the batch doesn't
    pipeline). Warm-path numbers: the first call pays compiles and cold
    caches, the second is the steady state the pipeline targets."""
    from lighthouse_tpu import jax_backend as jb
    from lighthouse_tpu.common import pipeline as pl

    out = sys.stderr if JSON_MODE else sys.stdout
    if not pl.should_pipeline(len(sets)):
        print(f"pipeline: skipped (enabled={pl.enabled()} "
              f"S={len(sets)} min_sets={pl.min_sets()})", file=out)
        return {"enabled": False}

    be = jb.JaxBackend()
    assert be.verify_signature_sets(sets)   # compiles + cold caches
    t0 = time.perf_counter()
    assert be.verify_signature_sets(sets)   # steady state
    wall = time.perf_counter() - t0
    pipe = jb.dispatch_stage_report().get("pipeline") or {}
    record("pipelined e2e (warm)", wall * 1e3)
    print(f"pipeline: chunks={pipe.get('chunks')} "
          f"chunk_size={pipe.get('chunk_size')} "
          f"overlap={pipe.get('overlap_s')}s "
          f"exposed={pipe.get('host_exposed_s')}s", file=out)
    for stage, d in sorted((pipe.get("stages") or {}).items()):
        print(f"  {stage:20s} hidden {d['hidden_s']*1e3:8.1f} ms   "
              f"exposed {d['exposed_s']*1e3:8.1f} ms", file=out)
    return pipe


if __name__ == "__main__":
    if DEVICES is not None:
        profile_multichip(DEVICES)
    else:
        main()
