"""Fault drill: the bench warm path under a matrix of injected faults.

For every (dispatch stage × fault kind) cell, injects one deterministic
fault via ``LHTPU_FAULT_INJECT=<stage>:<kind>:1`` and runs a warm
``verify_signature_sets`` batch through the resilient backend, then
checks the contract of `common/resilience.py`:

* a *transient* kind (``remote_compile`` — the literal r05 failure)
  must be absorbed by an in-stage retry: verdict True, >=1 retry
  recorded, no degradation;
* a *permanent* kind (``mosaic`` — the literal r04 failure) must trip
  the rung's circuit breaker and answer from a lower ladder rung:
  verdict True, >=1 degraded dispatch recorded.

Prints a pass/fail table (or one JSON line with ``--json``) and exits
nonzero if any cell broke the contract — so every rung of the
degradation ladder is exercised in CI without a TPU. ``--quick`` runs
a 3-stage subset (the tier-1 smoke in tests/test_resilience.py calls
run_drill directly with the same subset).

Usage:  python tools/fault_drill.py [--quick] [--json]
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from lighthouse_tpu.common.stages import CANONICAL_STAGES  # noqa: E402

#: the drillable subset of the canonical grammar: a new canonical stage
#: joins the drill matrix automatically; the multi-chip/fallback/bench
#: stages need topologies this host drill can't fake.
STAGES = tuple(
    s for s in CANONICAL_STAGES
    if s not in ("sharded_dispatch", "native_fallback", "bench_device")
)
QUICK_STAGES = ("hash_to_curve", "dispatch", "device_sync")
#: stages the grouped-triage path actually enters (it never builds an
#: MSM schedule — per-group accumulators are incompatible with the
#: single global MSM fold). Includes the ISSUE 10 hash sub-stages: a
#: dedup fault must degrade in place to the identity plan (bit-identical
#: verdicts), map/cofactor faults ride the normal ladder.
TRIAGE_STAGES = ("pack", "hash_to_curve", "htc_dedup", "htc_map",
                 "htc_cofactor", "scalars", "dispatch", "device_sync")

#: kind -> (classifier category, human label)
KINDS = (
    ("remote_compile", "transient"),
    ("mosaic", "permanent"),
)


def _mk_sets():
    """A tiny valid batch in the same (S=2, K=2) compile bucket the
    fast test tier already pays for."""
    from lighthouse_tpu.crypto.bls.api import (
        AggregateSignature,
        SecretKey,
        SignatureSet,
    )

    sks = [SecretKey.from_int(i + 7) for i in range(3)]
    m0, m1 = b"\x11" * 32, b"\x22" * 32
    s0 = SignatureSet.single_pubkey(sks[0].sign(m0), sks[0].public_key(), m0)
    agg = AggregateSignature.aggregate([sks[1].sign(m1), sks[2].sign(m1)])
    s1 = SignatureSet.multiple_pubkeys(
        agg, [sks[1].public_key(), sks[2].public_key()], m1
    )
    return [s0, s1]


def _total(counter) -> float:
    return sum(v for _, v in counter.items())


def run_drill(stages=STAGES, kinds=KINDS, sets=None, backend=None,
              pipelined: bool = False):
    """Run the injection matrix; returns a list of per-cell dicts with
    an ``ok`` verdict each. Restores the env and resilience state it
    touched (safe to call from tests).

    ``pipelined=True`` drills the microbatch pipeline instead of the
    single-shot dispatch: the batch is doubled to two chunks and
    LHTPU_PIPELINE forced on with a 2-set chunk size, so per-chunk
    retries and mid-pipeline breaker trips meet the SAME contract (each
    chunk stays in the (S=2, K=2) compile bucket the fast tier pays
    for)."""
    from lighthouse_tpu.common import resilience
    from lighthouse_tpu.jax_backend import JaxBackend

    if backend is None:
        backend = JaxBackend()
    if sets is None:
        sets = _mk_sets()
        if pipelined:
            sets = sets + _mk_sets()  # two chunks of the same bucket

    saved = {
        k: os.environ.get(k)
        for k in ("LHTPU_FAULT_INJECT", "LHTPU_RETRY_BASE_MS",
                  "LHTPU_PIPELINE", "LHTPU_PIPELINE_MIN_SETS",
                  "LHTPU_PIPELINE_CHUNK")
    }
    os.environ["LHTPU_RETRY_BASE_MS"] = "0"  # no backoff sleeps in a drill
    os.environ.pop("LHTPU_FAULT_INJECT", None)
    if pipelined:
        os.environ["LHTPU_PIPELINE"] = "1"
        os.environ["LHTPU_PIPELINE_MIN_SETS"] = "2"
        os.environ["LHTPU_PIPELINE_CHUNK"] = "2"
    else:
        os.environ["LHTPU_PIPELINE"] = "0"
    results = []
    try:
        # Healthy warm pass: pays the one compile and pins the baseline
        # verdict every drilled cell must reproduce.
        assert backend.verify_signature_sets(sets), "healthy warm pass failed"
        healthy_path = backend.last_path

        for stage in stages:
            for kind, category in kinds:
                resilience.reset()
                retries0 = _total(resilience.RETRIES_TOTAL)
                degraded0 = _total(resilience.DEGRADED_TOTAL)
                os.environ["LHTPU_FAULT_INJECT"] = f"{stage}:{kind}:1"
                error = None
                try:
                    verdict = backend.verify_signature_sets(sets)
                except Exception as exc:  # contract breach, not a crash
                    verdict = None
                    cat, kind_c = resilience.classify(exc)
                    error = f"{type(exc).__name__}: {exc} [{cat}/{kind_c}]"
                finally:
                    os.environ.pop("LHTPU_FAULT_INJECT", None)
                retries = _total(resilience.RETRIES_TOTAL) - retries0
                degraded = _total(resilience.DEGRADED_TOTAL) - degraded0
                if category == "transient":
                    ok = verdict is True and retries >= 1 and degraded == 0
                else:
                    ok = verdict is True and degraded >= 1
                results.append({
                    "mode": "pipelined" if pipelined else "single-shot",
                    "stage": stage,
                    "kind": kind,
                    "category": category,
                    "verdict": verdict,
                    "retries": retries,
                    "degraded": degraded,
                    "path": backend.last_path,
                    "healthy_path": healthy_path,
                    "error": error,
                    "ok": ok,
                })
    finally:
        for key, val in saved.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
        resilience.reset()
    return results


def _mk_poisoned_sets():
    """The triage drill batch: 4 sets, index 2 signed over the wrong
    message — round 1 at (S=4, G=2) plus one (S=2, G=2) gs=1 refinement,
    the same two compile buckets tests/test_triage.py pays for."""
    from lighthouse_tpu.crypto.bls.api import (
        AggregateSignature,
        SecretKey,
        SignatureSet,
    )

    sks = [SecretKey.from_int(i + 7) for i in range(6)]
    bad_msg = b"\xee" * 32
    sets = []
    for i in range(4):
        m = bytes([i + 1]) * 32
        signed = bad_msg if i == 2 else m
        if i % 2 == 0:
            sets.append(SignatureSet.single_pubkey(
                sks[i].sign(signed), sks[i].public_key(), m
            ))
        else:
            a, b = sks[i], sks[i + 2]
            agg = AggregateSignature.aggregate([a.sign(signed), b.sign(m)])
            sets.append(SignatureSet.multiple_pubkeys(
                agg, [a.public_key(), b.public_key()], m
            ))
    return sets, [True, True, False, True]


def run_drill_triaged(stages=TRIAGE_STAGES, kinds=KINDS, backend=None):
    """Poisoned-batch drill through verify_signature_sets_triaged
    (ISSUE 5): every cell must keep the per-set verdicts bit-correct —
    a transient is retried in place, a permanent fault may degrade to
    the host bisection (fallback recorded) but NEVER crash or flip a
    verdict."""
    from lighthouse_tpu import jax_backend as jb
    from lighthouse_tpu.common import resilience

    if backend is None:
        backend = jb.JaxBackend()
    sets, expected = _mk_poisoned_sets()

    saved = {
        k: os.environ.get(k)
        for k in ("LHTPU_FAULT_INJECT", "LHTPU_RETRY_BASE_MS",
                  "LHTPU_PIPELINE", "LHTPU_VERDICT_GROUPS")
    }
    os.environ["LHTPU_RETRY_BASE_MS"] = "0"
    os.environ["LHTPU_PIPELINE"] = "0"
    os.environ["LHTPU_VERDICT_GROUPS"] = "2"
    os.environ.pop("LHTPU_FAULT_INJECT", None)
    results = []
    try:
        got = backend.verify_signature_sets_triaged(sets)
        assert got == expected, f"healthy triage pass broken: {got}"
        healthy_path = backend.last_path

        for stage in stages:
            for kind, category in kinds:
                resilience.reset()
                retries0 = _total(resilience.RETRIES_TOTAL)
                degraded0 = _total(resilience.DEGRADED_TOTAL)
                os.environ["LHTPU_FAULT_INJECT"] = f"{stage}:{kind}:1"
                error = None
                try:
                    verdict = backend.verify_signature_sets_triaged(sets)
                except Exception as exc:  # contract breach, not a crash
                    verdict = None
                    cat, kind_c = resilience.classify(exc)
                    error = f"{type(exc).__name__}: {exc} [{cat}/{kind_c}]"
                finally:
                    os.environ.pop("LHTPU_FAULT_INJECT", None)
                retries = _total(resilience.RETRIES_TOTAL) - retries0
                degraded = _total(resilience.DEGRADED_TOTAL) - degraded0
                fallback = jb.dispatch_stage_report()["triage"].get(
                    "fallback"
                )
                if category == "transient":
                    ok = (verdict == expected and retries >= 1
                          and degraded == 0 and fallback is None)
                else:
                    ok = verdict == expected and degraded >= 1
                results.append({
                    "mode": "triaged",
                    "stage": stage,
                    "kind": kind,
                    "category": category,
                    "verdict": verdict == expected if verdict is not None
                    else None,
                    "retries": retries,
                    "degraded": degraded,
                    "path": backend.last_path,
                    "healthy_path": healthy_path,
                    "fallback": fallback,
                    "error": error,
                    "ok": ok,
                })
    finally:
        for key, val in saved.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
        resilience.reset()
    return results


def run_drill_slot_load(kinds=KINDS, backend=None):
    """Fault injection MID-SLOT into a loadgen replay (ISSUE 6
    satellite): a tiny deterministic 2-slot poison-storm stream is
    served through the ServingLoop on a virtual clock while
    ``dispatch:<kind>:1`` fires inside the first verification batch.
    Contract: the replay never crashes, every served verdict still
    matches the generator's ground truth (transient → retried in place;
    permanent → degraded to host bisection), and the SLO report stays
    well-formed.

    Shape economics: aggregate-only traffic at committee_size=2 with
    batch_target=4 and a 100 ms deadline dispatches partial batches of
    2 two-key sets — the (S=2, K=2, G=2) triage bucket
    tests/test_triage.py already pays for; no new compiles."""
    from lighthouse_tpu import jax_backend as jb
    from lighthouse_tpu.common import resilience
    from lighthouse_tpu.loadgen.serve import (
        ServeConfig,
        ServingLoop,
        VirtualClock,
    )
    from lighthouse_tpu.loadgen.traffic import (
        TrafficConfig,
        TrafficGenerator,
        expected_verdicts,
    )

    if backend is None:
        backend = jb.JaxBackend()

    cfg = TrafficConfig(
        validators=64, slots=2, seconds_per_slot=2.0,
        committees_per_slot=2, committee_size=2,
        unaggregated_per_slot=0, sync_per_slot=0, blocks=False,
        poison_rate=0.25, key_pool=8, seed=7,
    )
    gen = TrafficGenerator(cfg)

    def _serve():
        loop = ServingLoop(
            ServeConfig(batch_target=4, batch_deadline_ms=100.0),
            clock=VirtualClock(),
            verify=lambda sets: backend.verify_signature_sets_triaged(sets),
        )
        events = gen.generate()
        report = loop.run(events)
        return loop.verdicts, expected_verdicts(events), report

    saved = {
        k: os.environ.get(k)
        for k in ("LHTPU_FAULT_INJECT", "LHTPU_RETRY_BASE_MS",
                  "LHTPU_PIPELINE", "LHTPU_VERDICT_GROUPS")
    }
    os.environ["LHTPU_RETRY_BASE_MS"] = "0"
    os.environ["LHTPU_PIPELINE"] = "0"
    os.environ["LHTPU_VERDICT_GROUPS"] = "2"
    os.environ.pop("LHTPU_FAULT_INJECT", None)
    results = []
    try:
        got, expected, _ = _serve()  # healthy warm replay (pays compile)
        assert got == expected and any(not v for v in expected.values()), (
            f"healthy slot-load replay broken: {got} vs {expected}"
        )
        healthy_path = backend.last_path

        for kind, category in kinds:
            resilience.reset()
            retries0 = _total(resilience.RETRIES_TOTAL)
            degraded0 = _total(resilience.DEGRADED_TOTAL)
            os.environ["LHTPU_FAULT_INJECT"] = f"dispatch:{kind}:1"
            error = None
            verdicts_ok = None
            slo_ok = False
            try:
                got, expected, report = _serve()
                verdicts_ok = got == expected
                slo = report.get("slo") or {}
                slo_ok = all(
                    k in slo for k in
                    ("p50_ms", "p99_ms", "shed", "dropped", "within_budget")
                )
            except Exception as exc:  # contract breach, not a crash
                cat, kind_c = resilience.classify(exc)
                error = f"{type(exc).__name__}: {exc} [{cat}/{kind_c}]"
            finally:
                os.environ.pop("LHTPU_FAULT_INJECT", None)
            retries = _total(resilience.RETRIES_TOTAL) - retries0
            degraded = _total(resilience.DEGRADED_TOTAL) - degraded0
            if category == "transient":
                ok = bool(verdicts_ok) and slo_ok and retries >= 1 \
                    and degraded == 0
            else:
                ok = bool(verdicts_ok) and slo_ok and degraded >= 1
            results.append({
                "mode": "slot-load",
                "stage": "dispatch",
                "kind": kind,
                "category": category,
                "verdict": verdicts_ok,
                "retries": retries,
                "degraded": degraded,
                "path": backend.last_path,
                "healthy_path": healthy_path,
                "slo_ok": slo_ok,
                "error": error,
                "ok": ok,
            })
    finally:
        for key, val in saved.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
        resilience.reset()
    return results


#: multichip cells: faults fired INSIDE the sharded dispatch stage
#: (transient → retried in place on the mesh; permanent, including a
#: simulated chip loss, → circuit-break down to single-chip).
MULTICHIP_KINDS = (
    ("remote_compile", "transient"),
    ("mosaic", "permanent"),
    ("chip_loss", "permanent"),
)


def run_drill_multichip(kinds=MULTICHIP_KINDS, backend=None):
    """Sharded-dispatch drill (ISSUE 8): faults injected into the
    multi-chip composition while a batch spans the mesh.

    Contract per cell:

    * transient — the sharded dispatch is retried in place: verdict
      True, >=1 retry, the path STAYS sharded, no degradation;
    * permanent (``mosaic`` lowering bug, ``chip_loss`` device loss) —
      the sharded breaker opens and the SAME packed grids re-dispatch
      on one chip: verdict True (bit-identical), >=1 degraded dispatch,
      ``path`` records the ``+sharded-fallback`` rung.

    Returns [] when the process has fewer than 2 devices (the mesh
    can't form; main() forces an 8-way host mesh before jax init so the
    standalone drill always exercises these rows).
    """
    from lighthouse_tpu.common import resilience
    from lighthouse_tpu.crypto.bls.api import SecretKey, SignatureSet
    from lighthouse_tpu.jax_backend import JaxBackend
    from lighthouse_tpu.parallel import engine

    if engine.topology().n_devices < 2:
        return []
    if backend is None:
        backend = JaxBackend()

    # 8 single-pubkey sets: the (S=8, K=1) bucket the sharded test tier
    # already compiles, one real set per chip on an 8-way mesh.
    sks = [SecretKey.from_int(i + 7) for i in range(8)]
    msgs = [bytes([i + 1]) * 32 for i in range(8)]
    sets = [
        SignatureSet.single_pubkey(sks[i].sign(msgs[i]),
                                   sks[i].public_key(), msgs[i])
        for i in range(8)
    ]

    saved = {
        k: os.environ.get(k)
        for k in ("LHTPU_FAULT_INJECT", "LHTPU_RETRY_BASE_MS",
                  "LHTPU_PIPELINE", "LHTPU_SHARDED_VERIFY",
                  "LHTPU_DEVICES")
    }
    os.environ["LHTPU_RETRY_BASE_MS"] = "0"
    os.environ["LHTPU_PIPELINE"] = "0"
    os.environ["LHTPU_SHARDED_VERIFY"] = "1"
    os.environ.pop("LHTPU_FAULT_INJECT", None)
    results = []
    try:
        resilience.reset()
        engine.reset()
        assert backend.verify_signature_sets(sets), \
            "healthy sharded warm pass failed"
        healthy_path = backend.last_path
        assert "sharded" in healthy_path, (
            f"sharded path did not engage: {healthy_path}"
        )

        for kind, category in kinds:
            resilience.reset()
            engine.reset()
            retries0 = _total(resilience.RETRIES_TOTAL)
            degraded0 = _total(resilience.DEGRADED_TOTAL)
            os.environ["LHTPU_FAULT_INJECT"] = f"sharded_dispatch:{kind}:1"
            error = None
            try:
                verdict = backend.verify_signature_sets(sets)
            except Exception as exc:  # contract breach, not a crash
                verdict = None
                cat, kind_c = resilience.classify(exc)
                error = f"{type(exc).__name__}: {exc} [{cat}/{kind_c}]"
            finally:
                os.environ.pop("LHTPU_FAULT_INJECT", None)
            retries = _total(resilience.RETRIES_TOTAL) - retries0
            degraded = _total(resilience.DEGRADED_TOTAL) - degraded0
            path = backend.last_path
            if category == "transient":
                ok = (verdict is True and retries >= 1 and degraded == 0
                      and "sharded" in path
                      and "+sharded-fallback" not in path)
            else:
                ok = (verdict is True and degraded >= 1
                      and path.endswith("+sharded-fallback"))
            results.append({
                "mode": "multichip",
                "stage": "sharded_dispatch",
                "kind": kind,
                "category": category,
                "verdict": verdict,
                "retries": retries,
                "degraded": degraded,
                "path": path,
                "healthy_path": healthy_path,
                "reason": engine.parallel_report().get("reason"),
                "error": error,
                "ok": ok,
            })
    finally:
        for key, val in saved.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
        resilience.reset()
        engine.reset()
    return results


def run_drill_lazy(backend=None):
    """ISSUE 18 cell: a mosaic fault while the LAZY-REDUCTION pairing
    tower is live must degrade down the ladder with per-set verdicts
    bit-identical to the strict baseline — the knob changes limb
    representatives mid-chain, never verdicts, and a faulted lazy
    dispatch must land on a rung that agrees with strict bit-for-bit.

    The knobs are read at TRACE time, so the in-process jit caches are
    dropped around the flip (the persistent .jax_cache absorbs the
    recompiles after the first run)."""
    import jax

    from lighthouse_tpu import jax_backend as jb
    from lighthouse_tpu.common import resilience

    if backend is None:
        backend = jb.JaxBackend()
    sets, expected = _mk_poisoned_sets()

    saved = {
        k: os.environ.get(k)
        for k in ("LHTPU_FAULT_INJECT", "LHTPU_RETRY_BASE_MS",
                  "LHTPU_PIPELINE", "LHTPU_VERDICT_GROUPS",
                  "LHTPU_LAZY_REDUCE", "LHTPU_MXU_CARRY")
    }
    os.environ["LHTPU_RETRY_BASE_MS"] = "0"
    os.environ["LHTPU_PIPELINE"] = "0"
    os.environ["LHTPU_VERDICT_GROUPS"] = "2"
    os.environ.pop("LHTPU_FAULT_INJECT", None)
    os.environ.pop("LHTPU_LAZY_REDUCE", None)
    os.environ.pop("LHTPU_MXU_CARRY", None)
    results = []
    try:
        baseline = backend.verify_signature_sets_triaged(sets)
        assert baseline == expected, f"strict baseline broken: {baseline}"

        os.environ["LHTPU_LAZY_REDUCE"] = "1"
        jax.clear_caches()
        healthy = backend.verify_signature_sets_triaged(sets)
        lazy_parity = healthy == baseline

        resilience.reset()
        retries0 = _total(resilience.RETRIES_TOTAL)
        degraded0 = _total(resilience.DEGRADED_TOTAL)
        os.environ["LHTPU_FAULT_INJECT"] = "dispatch:mosaic:1"
        error = None
        try:
            verdict = backend.verify_signature_sets_triaged(sets)
        except Exception as exc:  # contract breach, not a crash
            verdict = None
            cat, kind_c = resilience.classify(exc)
            error = f"{type(exc).__name__}: {exc} [{cat}/{kind_c}]"
        finally:
            os.environ.pop("LHTPU_FAULT_INJECT", None)
        retries = _total(resilience.RETRIES_TOTAL) - retries0
        degraded = _total(resilience.DEGRADED_TOTAL) - degraded0
        if not lazy_parity:
            error = (error or "") + f" lazy healthy pass != strict: {healthy}"
        results.append({
            "mode": "lazy-tower",
            "stage": "dispatch",
            "kind": "mosaic",
            "category": "permanent",
            "verdict": verdict == baseline if verdict is not None else None,
            "retries": retries,
            "degraded": degraded,
            "path": backend.last_path,
            "healthy_path": None,
            "error": error or None,
            "ok": lazy_parity and verdict == baseline and degraded >= 1,
        })
    finally:
        for key, val in saved.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
        resilience.reset()
        jax.clear_caches()  # drop the lazy-traced programs
    return results


def run_drill_soak():
    """Multi-epoch soak drill (ISSUE 7): two endurance cells over
    ``loadgen/soak.SoakRunner`` on the virtual clock, aggregate-only
    traffic pinned to the (S=2, K=2, G=2) bucket the other rows pay
    for (batch_target=2 with a deadline past within-slot jitter, so
    per-epoch seed shifts can never form an odd-sized batch that would
    need a fresh device program mid-soak).

    * ``transient mid-soak``: one ``dispatch:remote_compile`` fault at
      epoch 1 of 3 — the run must PASS, re-promote to the primary rung
      within the recovery budget, and its per-epoch verdict digests
      must match the chaos-free replay bit-for-bit.
    * ``permanent sustained``: ``dispatch:mosaic`` at epochs 1 and 2 of
      3 — the run must end DEGRADED (breakers open, host bisection
      serving), never crash, and still keep every verdict correct."""
    from lighthouse_tpu.common import health, resilience
    from lighthouse_tpu.loadgen.serve import ServeConfig
    from lighthouse_tpu.loadgen.soak import ChaosEvent, SoakConfig, SoakRunner
    from lighthouse_tpu.loadgen.traffic import TrafficConfig

    saved = {
        k: os.environ.get(k)
        for k in ("LHTPU_FAULT_INJECT", "LHTPU_RETRY_BASE_MS",
                  "LHTPU_PIPELINE", "LHTPU_VERDICT_GROUPS",
                  "LHTPU_BREAKER_COOLDOWN_S")
    }
    os.environ["LHTPU_RETRY_BASE_MS"] = "0"
    os.environ["LHTPU_PIPELINE"] = "0"
    os.environ["LHTPU_VERDICT_GROUPS"] = "2"
    # breakers must half-open inside the drill's wall time
    os.environ["LHTPU_BREAKER_COOLDOWN_S"] = "0.01"
    os.environ.pop("LHTPU_FAULT_INJECT", None)
    # Deterministic sentinels only: the RSS/jit-cache sentinels react to
    # unrelated compile activity earlier in the drill matrix.
    health.configure(sentinels=[
        health.BreakerFlapSentinel(), health.SloBreachSentinel(),
    ])

    def _cfg(replay: bool) -> SoakConfig:
        return SoakConfig(
            epochs=3, seed=7, backend="jax", recovery_epochs=2,
            replay=replay,
            traffic=TrafficConfig(
                validators=64, slots=2, seconds_per_slot=2.0,
                committees_per_slot=2, committee_size=2,
                unaggregated_per_slot=0, sync_per_slot=0, blocks=False,
                poison_rate=0.25, key_pool=8, seed=7,
            ),
            serve=ServeConfig(batch_target=2, batch_deadline_ms=1000.0),
        )

    cells = (
        ("remote_compile", "transient",
         [ChaosEvent(epoch=1, stage="dispatch",
                     kind="remote_compile", count=1)], True),
        ("mosaic", "permanent",
         [ChaosEvent(epoch=e, stage="dispatch", kind="mosaic", count=1)
          for e in (1, 2)], False),
    )
    results = []
    try:
        for kind, category, chaos, replay in cells:
            resilience.reset()
            retries0 = _total(resilience.RETRIES_TOTAL)
            degraded0 = _total(resilience.DEGRADED_TOTAL)
            error = None
            res = None
            try:
                res = SoakRunner(_cfg(replay), chaos=chaos, emit=None).run()
            except Exception as exc:  # contract breach, not a crash
                cat, kind_c = resilience.classify(exc)
                error = f"{type(exc).__name__}: {exc} [{cat}/{kind_c}]"
            retries = _total(resilience.RETRIES_TOTAL) - retries0
            degraded = _total(resilience.DEGRADED_TOTAL) - degraded0
            if res is None:
                ok = False
            elif category == "transient":
                # chaos absorbed: verdict passes end-to-end, the ladder
                # re-promotes, and the replay digests are bit-identical
                ok = (res["verdict"] == "pass"
                      and res["mismatches_total"] == 0
                      and res["repromotion"]["required"]
                      and res["repromotion"]["ok"]
                      and res["replay"]["digests_match"] is True)
            else:
                # sustained permanent: degrade (both chaos epochs), keep
                # verdicts exact, never crash or wedge
                ok = (res is not None
                      and not any(r.startswith("crashed")
                                  for r in res["reasons"])
                      and res["mismatches_total"] == 0
                      and res["degraded_epochs"] >= 2
                      and res["degraded_time_fraction"] < 1.0
                      and res["watchdog_fired"] == 0)
            results.append({
                "mode": "soak",
                "stage": "dispatch",
                "kind": kind,
                "category": category,
                "verdict": (res["mismatches_total"] == 0
                            if res is not None else None),
                "retries": retries,
                "degraded": degraded,
                "path": None if res is None else f"soak:{res['verdict']}",
                "healthy_path": None,
                "degraded_time_fraction":
                    res["degraded_time_fraction"] if res else None,
                "error": error,
                "ok": ok,
            })
    finally:
        for key, val in saved.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
        resilience.reset()
        health.reset()
    return results


def run_drill_stream():
    """Continuous-stream drill (ISSUE 15): faults injected into the
    cross-slot StreamScheduler mid-stream over two epochs of mixed
    block + aggregate + attestation traffic on the virtual clock.

    Contract per cell (and the scheduler invariants the matrix pins):

    * ``transient mid-stream`` (``dispatch:remote_compile``) — retried
      in place: zero mismatches, >=1 retry, no rung degradation;
    * ``permanent mid-stream`` (``dispatch:mosaic``) — degrades down
      the ladder (>=1 degraded dispatch) with every verdict still
      correct;
    * ``cache fault`` (``sched_cache:assert``) — the composition cache
      degrades to the identity transform in place: >=1 recorded cache
      fault, zero mismatches (a cache fault may cost the dedup win,
      never a verdict);
    * ``preempted`` (no injected fault) — a block arriving inside an
      attestation coalescing window preempts the remainder, which
      re-enqueues EXACTLY once: preemptions >=1, every event served
      once, and the offered == served+shed+dropped+pending accounting
      identity stays balanced.

    Every cell additionally requires zero blocks shed or dropped."""
    from lighthouse_tpu import jax_backend as jb
    from lighthouse_tpu.common import resilience
    from lighthouse_tpu.loadgen.scheduler import (
        SchedulerConfig,
        StreamRunner,
        StreamScheduler,
    )
    from lighthouse_tpu.loadgen.serve import VirtualClock
    from lighthouse_tpu.loadgen.traffic import (
        TimedEvent,
        TrafficConfig,
        TrafficGenerator,
    )
    from lighthouse_tpu.network.processor import WorkType

    backend = jb.JaxBackend()
    traffic = TrafficConfig(
        validators=64, slots=2, seconds_per_slot=2.0,
        committees_per_slot=2, committee_size=2,
        unaggregated_per_slot=2, sync_per_slot=0, blocks=True,
        poison_rate=0.25, key_pool=8, seed=7, peers=4,
    )

    def _sched_cfg(**over) -> SchedulerConfig:
        base = dict(
            batch_target=4, agg_deadline_ms=100.0, att_deadline_ms=100.0,
            sync_deadline_ms=100.0, dispatch_ms=0.0, cache=False,
        )
        base.update(over)
        return SchedulerConfig(**base)

    def _run(chaos: str, **cfg_over) -> dict:
        runner = StreamRunner(
            traffic, 2, _sched_cfg(**cfg_over), clock=VirtualClock(),
            verify=lambda sets: backend.verify_signature_sets_triaged(sets),
            chaos=chaos, emit=None,
        )
        return runner.run()

    saved = {
        k: os.environ.get(k)
        for k in ("LHTPU_FAULT_INJECT", "LHTPU_RETRY_BASE_MS",
                  "LHTPU_PIPELINE", "LHTPU_VERDICT_GROUPS")
    }
    os.environ["LHTPU_RETRY_BASE_MS"] = "0"
    os.environ["LHTPU_PIPELINE"] = "0"
    os.environ["LHTPU_VERDICT_GROUPS"] = "2"
    os.environ.pop("LHTPU_FAULT_INJECT", None)

    cells = (
        ("remote_compile", "transient", "0:dispatch:remote_compile:1", {}),
        ("mosaic", "permanent", "0:dispatch:mosaic:1", {}),
        ("assert", "cache", "0:sched_cache:assert:1", {"cache": True}),
        ("preempted", "preempt", "", {}),
    )
    results = []
    try:
        healthy = _run("")  # healthy warm run (pays the compiles)
        assert healthy["verdicts"]["mismatches"] == 0, (
            f"healthy stream run broken: {healthy['verdicts']}"
        )
        healthy_path = backend.last_path

        for kind, category, chaos, cfg_over in cells:
            resilience.reset()
            retries0 = _total(resilience.RETRIES_TOTAL)
            degraded0 = _total(resilience.DEGRADED_TOTAL)
            error = None
            rep = None
            preempted = 0
            try:
                if category == "preempt":
                    # Crafted window: a full attestation batch opens at
                    # t=0 with modeled dispatch occupancy; the block
                    # lands inside the window and must preempt it.
                    events = TrafficGenerator(traffic).generate()
                    atts = [te for te in events if te.event.work_type
                            is WorkType.GOSSIP_ATTESTATION]
                    aggs = [te for te in events if te.event.work_type
                            is WorkType.GOSSIP_AGGREGATE]
                    blocks = [te for te in events if te.event.work_type
                              is WorkType.GOSSIP_BLOCK]
                    stream = [TimedEvent(t=0.0, event=te.event)
                              for te in atts + aggs]
                    stream += [TimedEvent(t=0.005 + i * 0.001,
                                          event=te.event)
                               for i, te in enumerate(blocks)]
                    stream.sort(key=lambda te: te.t)
                    sched = StreamScheduler(
                        _sched_cfg(batch_target=8, att_deadline_ms=0.0,
                                   agg_deadline_ms=0.0, dispatch_ms=10.0),
                        clock=VirtualClock(),
                        verify=lambda sets:
                            backend.verify_signature_sets_triaged(sets),
                    )
                    rep = sched.run(stream)
                    preempted = rep["sched"]["preempted_batches"]
                else:
                    rep = _run(chaos, **cfg_over)
                    preempted = rep["sched"]["preempted_batches"]
            except Exception as exc:  # contract breach, not a crash
                cat, kind_c = resilience.classify(exc)
                error = f"{type(exc).__name__}: {exc} [{cat}/{kind_c}]"
            retries = _total(resilience.RETRIES_TOTAL) - retries0
            degraded = _total(resilience.DEGRADED_TOTAL) - degraded0
            if rep is None:
                ok = False
            else:
                block = rep["sched"]["block"]
                base_ok = (rep["verdicts"]["mismatches"] == 0
                           and block["shed"] == 0
                           and block["dropped"] == 0
                           and rep["accounting"]["balanced"])
                if category == "transient":
                    ok = base_ok and retries >= 1 and degraded == 0
                elif category == "permanent":
                    ok = base_ok and degraded >= 1
                elif category == "cache":
                    ok = (base_ok
                          and rep["sched"]["cache"]["faults"] >= 1)
                else:  # preempt: exactly-once re-enqueue accounting
                    ok = (base_ok and preempted >= 1
                          and rep["accounting"]["pending"] == 0
                          and rep["events_served"]
                          == rep["events_offered"]
                          - rep["slo"]["shed"] - rep["slo"]["dropped"])
            results.append({
                "mode": "stream",
                "stage": "sched_cache" if category == "cache"
                         else "dispatch",
                "kind": kind,
                "category": category,
                "verdict": (rep["verdicts"]["mismatches"] == 0
                            if rep is not None else None),
                "retries": retries,
                "degraded": degraded,
                "preempted": preempted,
                "path": backend.last_path,
                "healthy_path": healthy_path,
                "error": error,
                "ok": ok,
            })
    finally:
        for key, val in saved.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
        resilience.reset()
    return results


def run_drill_weather():
    """Chain-weather drill (ISSUE 17): faults injected while the
    stream is under adversarial weather.

    * ``reorg_transient`` — a transient dispatch fault during a reorg
      storm retries in place: zero mismatches, >=1 retry, no rung
      degradation, competing-head blocks all served (never shed);
    * ``flood_permanent`` — a permanent fault mid slashing-flood
      degrades down the ladder without shedding a single block, and
      attestations keep being served (the starvation guard's contract);
    * ``slasher`` — a ``slasher``-stage fault falls back to the host
      scan with IDENTICAL findings: the degraded run's findings digest
      must equal the clean run's bit-for-bit, with >=1 recorded
      fallback.

    Every cell additionally requires its scenario SLOs to pass."""
    from lighthouse_tpu import jax_backend as jb
    from lighthouse_tpu.common import resilience
    from lighthouse_tpu.loadgen.scheduler import (
        SchedulerConfig,
        StreamRunner,
    )
    from lighthouse_tpu.loadgen.serve import VirtualClock
    from lighthouse_tpu.loadgen.traffic import TrafficConfig

    backend = jb.JaxBackend()
    traffic = TrafficConfig(
        validators=64, slots=2, seconds_per_slot=2.0,
        committees_per_slot=2, committee_size=2,
        unaggregated_per_slot=2, sync_per_slot=1, blocks=True,
        poison_rate=0.25, key_pool=8, seed=7, peers=4,
    )

    def _run(chaos: str, weather: str) -> dict:
        runner = StreamRunner(
            traffic, 2,
            SchedulerConfig(
                batch_target=4, agg_deadline_ms=100.0,
                att_deadline_ms=100.0, sync_deadline_ms=100.0,
                slashing_deadline_ms=100.0, dispatch_ms=0.0, cache=False,
            ),
            clock=VirtualClock(),
            verify=lambda sets: backend.verify_signature_sets_triaged(sets),
            chaos=chaos, emit=None, weather=weather,
        )
        return runner.run()

    saved = {
        k: os.environ.get(k)
        for k in ("LHTPU_FAULT_INJECT", "LHTPU_RETRY_BASE_MS",
                  "LHTPU_PIPELINE", "LHTPU_VERDICT_GROUPS",
                  "LHTPU_SLASHER_DEVICE", "LHTPU_SLASHER_CHUNK",
                  "LHTPU_SLASHER_HISTORY")
    }
    os.environ["LHTPU_RETRY_BASE_MS"] = "0"
    os.environ["LHTPU_PIPELINE"] = "0"
    os.environ["LHTPU_VERDICT_GROUPS"] = "2"
    # Drill-sized sink engine on the host scan: the fault/fallback
    # contract is mode-independent and this keeps the matrix compiles
    # pinned to the cached buckets.
    os.environ["LHTPU_SLASHER_DEVICE"] = "0"
    os.environ["LHTPU_SLASHER_CHUNK"] = "64"
    os.environ["LHTPU_SLASHER_HISTORY"] = "64"
    os.environ.pop("LHTPU_FAULT_INJECT", None)

    flood = "*:slashing_flood:2.0"
    cells = (
        ("remote_compile", "reorg_transient",
         "0:dispatch:remote_compile:1", "*:reorg_storm:0.9"),
        ("mosaic", "flood_permanent", "0:dispatch:mosaic:1", flood),
        ("assert", "slasher", "0:slasher:assert:1", flood),
    )
    results = []
    try:
        resilience.reset()
        clean_digest = _run("", flood)["sched"]["slasher"]["findings_digest"]

        for kind, category, chaos, weather in cells:
            resilience.reset()
            retries0 = _total(resilience.RETRIES_TOTAL)
            degraded0 = _total(resilience.DEGRADED_TOTAL)
            error = None
            rep = None
            try:
                rep = _run(chaos, weather)
            except Exception as exc:  # contract breach, not a crash
                cat, kind_c = resilience.classify(exc)
                error = f"{type(exc).__name__}: {exc} [{cat}/{kind_c}]"
            retries = _total(resilience.RETRIES_TOTAL) - retries0
            degraded = _total(resilience.DEGRADED_TOTAL) - degraded0
            if rep is None:
                ok = False
            else:
                block = rep["sched"]["block"]
                base_ok = (rep["verdicts"]["mismatches"] == 0
                           and block["shed"] == 0
                           and block["dropped"] == 0
                           and rep["accounting"]["balanced"]
                           and rep["scenarios"]["ok"])
                if category == "reorg_transient":
                    ok = base_ok and retries >= 1 and degraded == 0
                elif category == "flood_permanent":
                    served = rep["slo"]["per_class"]
                    ok = (base_ok and degraded >= 1
                          and served["attestation"]["served"] > 0)
                else:  # slasher fault: host fallback, findings intact
                    sl = rep["sched"]["slasher"]
                    engine = sl["engine"] or {}
                    ok = (base_ok
                          and engine.get("fallbacks", 0) >= 1
                          and sl["findings_digest"] == clean_digest)
            results.append({
                "mode": "weather",
                "stage": "slasher" if category == "slasher"
                         else "dispatch",
                "kind": kind,
                "category": category,
                "verdict": (rep["verdicts"]["mismatches"] == 0
                            if rep is not None else None),
                "retries": retries,
                "degraded": degraded,
                "path": backend.last_path,
                "healthy_path": None,
                "error": error,
                "ok": ok,
            })
    finally:
        for key, val in saved.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
        resilience.reset()
    return results


def main() -> int:
    json_mode = "--json" in sys.argv
    stages = QUICK_STAGES if "--quick" in sys.argv else STAGES
    out = sys.stderr if json_mode else sys.stdout

    # Force an 8-way host mesh BEFORE jax initializes so the multichip
    # rows always run (the flag only affects the host CPU platform —
    # real TPU meshes are untouched).
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

    triage_stages = QUICK_STAGES if "--quick" in sys.argv else TRIAGE_STAGES
    n_multichip = len(MULTICHIP_KINDS) if len(jax.devices()) > 1 else 0
    print(f"device={jax.devices()[0].platform} "
          f"cells={(len(stages) + len(QUICK_STAGES) + len(triage_stages) + 1) * len(KINDS) + 2 + n_multichip + 4 + 3 + 1}",
          file=out)
    results = run_drill(stages=stages)
    # Pipelined matrix (3-stage subset): per-chunk retry and
    # mid-pipeline breaker trips must meet the same contract.
    results += run_drill(stages=QUICK_STAGES, pipelined=True)
    # Poisoned-batch triage matrix (ISSUE 5): per-set verdicts must
    # survive every cell — degrade to host bisection, never crash.
    results += run_drill_triaged(stages=triage_stages)
    # Serving-loop matrix (ISSUE 6): transients injected mid-slot into
    # a loadgen poison-storm replay — degrade, never crash.
    results += run_drill_slot_load()
    # Multichip matrix (ISSUE 8): faults inside the sharded dispatch —
    # transients retried on the mesh, chip loss degrades to one chip.
    results += run_drill_multichip()
    # Soak matrix (ISSUE 7): multi-epoch chaos → re-promotion + digest
    # parity; sustained permanents degrade, never crash.
    results += run_drill_soak()
    # Continuous-stream matrix (ISSUE 15): faults mid-stream through
    # the cross-slot scheduler — transients retry in place, permanents
    # degrade down the ladder, a cache fault degrades to the identity
    # transform, blocks are never shed, and preemption-abandoned
    # batches re-enqueue exactly once.
    results += run_drill_stream()
    # Chain-weather matrix (ISSUE 17): faults during reorg storms and
    # slashing floods — retries in place / ladder degradation with
    # blocks never shed, and a slasher-stage fault falling back to the
    # host scan with bit-identical findings.
    results += run_drill_weather()
    # Lazy-tower cell (ISSUE 18): a mosaic fault with LHTPU_LAZY_REDUCE
    # live must degrade to a rung bit-identical to the strict baseline.
    # Runs LAST: it clears the in-process jit caches around the knob
    # flip, which would force earlier drills to re-trace.
    results += run_drill_lazy()
    failed = [r for r in results if not r["ok"]]

    header = (f"{'mode':12s} {'stage':14s} {'kind':16s} {'class':10s} "
              f"{'verdict':8s} {'retries':8s} {'degraded':9s} "
              f"{'path':22s} result")
    print(header, file=out)
    print("-" * len(header), file=out)
    for r in results:
        print(
            f"{r['mode']:12s} {r['stage']:14s} {r['kind']:16s} "
            f"{r['category']:10s} "
            f"{str(r['verdict']):8s} {r['retries']:<8.0f} "
            f"{r['degraded']:<9.0f} {str(r['path']):22s} "
            f"{'PASS' if r['ok'] else 'FAIL' + (' ' + r['error'] if r['error'] else '')}",
            file=out,
        )
    print(f"fault drill: {len(results) - len(failed)}/{len(results)} cells "
          f"passed", file=out)
    if json_mode:
        print(json.dumps({
            "metric": "fault_drill_cells_passed",
            "value": len(results) - len(failed),
            "unit": "cells",
            "vs_baseline": 0.0,
            "detail": {"cells": len(results), "results": results},
        }), flush=True)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
