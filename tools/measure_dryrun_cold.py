"""Measure dryrun_multichip(8) cold wall time, emulating the driver host.

Redirects the persistent compile cache to an empty temp dir so every XLA
compile is cold (the committed .jax_cache doesn't AOT-load cross-machine —
MULTICHIP_r03.json tail), then runs the gate exactly as the driver does.
"""

import os
import sys
import tempfile
import time

os.environ.setdefault(
    "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

cold = sys.argv[1] if len(sys.argv) > 1 else tempfile.mkdtemp(prefix="cold_jax_cache_")

import jax  # noqa: E402

_orig_update = jax.config.update


def _patched(name, val):
    if name == "jax_compilation_cache_dir":
        val = cold
    _orig_update(name, val)


jax.config.update = _patched

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import __graft_entry__  # noqa: E402

t0 = time.time()
__graft_entry__.dryrun_multichip(8)
print(f"TOTAL COLD WALL: {time.time() - t0:.1f}s", flush=True)
