"""TPU lowering-smoke gate (VERDICT r4 item 2).

Round 4 was lost to an env-default flip (LHTPU_KS_CARRY=1) that broke
Mosaic lowering of every fused Pallas kernel — committed without ever
compiling on TPU, invisible to the CPU-only fast tier. This gate makes
that class of regression impossible to ship:

  python tools/lowering_smoke.py            # fast set, <60 s
  python tools/lowering_smoke.py --full     # every production kernel (~10 min)
  python tools/lowering_smoke.py --run      # + execute one fused verify on TPU

The trick: ``jax.export`` with ``platforms=['tpu']`` runs the FULL
Pallas->Mosaic lowering pass (jax/_src/pallas/mosaic/lowering.py) on any
host — no TPU needed. The exact NotImplementedError that zeroed
BENCH_r04 reproduces in seconds on a 1-core CPU box. Each kernel is
lowered under BOTH carry paths (LHTPU_KS_CARRY=0 and =1) so a default
flip in either direction is covered.

RULE (README "Lowering smoke" section): run the fast set before every
commit that touches ops/ or jax_backend.py; run --full before flipping
any kernel-affecting env default. The fast tier also runs the cheapest
case as a pytest (tests/test_lowering_smoke.py).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mk_cases(full: bool):
    """(name, build_fn, args) per production kernel, tiny shapes (S=128:
    one lane tile). Import inside so env mutation precedes jax import."""
    import jax
    import jax.numpy as jnp

    from lighthouse_tpu.jax_backend import _rand_bits_array
    from lighthouse_tpu.ops import tkernel_calls as tc
    from lighthouse_tpu.ops.points import G1_GEN_DEV, G2_GEN_DEV

    S = 128
    g1x = jnp.broadcast_to(jnp.asarray(G1_GEN_DEV[0])[:, None], (48, S))
    g1y = jnp.broadcast_to(jnp.asarray(G1_GEN_DEV[1])[:, None], (48, S))
    g2x = jnp.broadcast_to(jnp.asarray(G2_GEN_DEV[0])[..., None], (2, 48, S))
    g2y = jnp.broadcast_to(jnp.asarray(G2_GEN_DEV[1])[..., None], (2, 48, S))
    inf_row = jnp.zeros((1, S), jnp.int32)
    bits_t = jnp.transpose(jnp.asarray(_rand_bits_array(S)))

    # Fast set: cheapest-to-trace kernels that still exercise every
    # carry/mont-mul code path (add/sub/canonical/mont_mul ride inside
    # the group law — Fp via the G1 ladder, Fp2 via the MSM mixed-add).
    cases = [
        ("scalar_mul_g1", lambda: tc.scalar_mul_g1_t(g1x, g1y, inf_row, bits_t)),
    ]

    def msm_accum():
        from lighthouse_tpu.ops import msm as _msm

        L = 8  # one grid step (schedule depth is padded to multiples of 8)
        W = _msm._LANES
        gx = jnp.broadcast_to(
            jnp.asarray(G2_GEN_DEV[0])[None, ..., None], (L, 2, 48, W))
        gy = jnp.broadcast_to(
            jnp.asarray(G2_GEN_DEV[1])[None, ..., None], (L, 2, 48, W))
        valid = jnp.ones((L, 1, W), jnp.int32)
        return _msm._accum_t(gx, gy, valid, False)

    cases.append(("msm_accum", msm_accum))

    if full:
        def sswu():
            from lighthouse_tpu.ops.tkernel_htc import _sswu_iso_t

            return _sswu_iso_t(g2x, False)

        def cofactor():
            from lighthouse_tpu.ops.tkernel_htc import _cofactor_t

            jac2 = (g2x, g2y, jnp.broadcast_to(
                jnp.concatenate(
                    [jnp.asarray(tc.tk._c("R"))[None],
                     jnp.zeros((1, 48, 1), jnp.int32)]
                ),
                (2, 48, S),
            ))
            return _cofactor_t(jac2, False)

        def final_exp():
            f = jnp.broadcast_to(
                jnp.zeros((2, 3, 2, 48, 1), jnp.int32)
                .at[0, 0, 0].set(tc.tk._c("R")),
                (2, 3, 2, 48, S),
            )
            return tc.final_exp_kernel_t(f)

        cases += [
            ("scalar_mul_g2", lambda: tc.scalar_mul_g2_t(
                g2x, g2y, inf_row, bits_t)),
            ("subgroup_fast", lambda: tc.subgroup_check_g2_fast_t(
                g2x, g2y, inf_row)),
            ("to_affine_g1", lambda: tc.to_affine_g1_t(
                (g1x, g1y, jnp.broadcast_to(tc.tk._c("R"), (48, S))))),
            ("miller", lambda: tc.miller_loop_kernel_t(
                (g1x, g1y), inf_row[0] != 0, (g2x, g2y), inf_row[0] != 0)),
            ("sswu_iso", sswu),
            ("cofactor", cofactor),
            ("final_exp", final_exp),
        ]
    return cases


def _lower_all(full: bool, ks: str) -> list[str]:
    """Export-lower every case for platform 'tpu' in THIS process with
    LHTPU_KS_CARRY=ks. Returns failure strings."""
    os.environ["LHTPU_KS_CARRY"] = ks
    # Mosaic lowering needs no device; force-exercise the TPU kernel
    # path (interpret mode off) regardless of host platform.
    os.environ.setdefault("LHTPU_MXU_FOLD", "1")

    import jax

    fails = []
    for name, fn in _mk_cases(full):
        t0 = time.time()
        try:
            jax.export.export(jax.jit(fn), platforms=["tpu"])()
            print(f"  ks={ks} {name:16s} lowered OK ({time.time() - t0:.0f}s)",
                  flush=True)
        except Exception as e:
            print(f"  ks={ks} {name:16s} FAILED: {str(e)[:160]}", flush=True)
            fails.append(f"ks={ks} {name}: {str(e)[:200]}")
    return fails


def _run_fused_verify() -> list[str]:
    """Execute one tiny fused verify on the attached TPU (the final
    word: lowering AND Mosaic compile AND numerics). Uses the
    persistent cache; a code change invalidates it, which is the
    point."""
    import jax

    if jax.default_backend() != "tpu":
        return [f"--run requires a TPU backend (got {jax.default_backend()})"]

    jax.config.update(
        "jax_compilation_cache_dir", os.path.join(_REPO, ".jax_cache_tpu")
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)

    import jax.numpy as jnp

    from lighthouse_tpu.crypto.bls.api import SecretKey, SignatureSet
    from lighthouse_tpu.crypto.bls.hash_to_curve import hash_to_g2
    from lighthouse_tpu.jax_backend import (
        _rand_scalars,
        _verify_fused_jit,
    )
    from lighthouse_tpu.ops import msm as _msm
    from lighthouse_tpu.ops.points import g1_to_dev, g2_to_dev

    S = 4
    sks = [SecretKey.from_int(i + 101) for i in range(S)]
    msgs = [i.to_bytes(32, "big") for i in range(S)]
    sets = [
        SignatureSet.single_pubkey(sk.sign(m), sk.public_key(), m)
        for sk, m in zip(sks, msgs)
    ]
    px, py, pinf = g1_to_dev([s.signing_keys[0].point for s in sets])
    px, py = px.reshape(S, 1, 48), py.reshape(S, 1, 48)
    pinf = pinf.reshape(S, 1)
    sx, sy, sinf = g2_to_dev([s.signature.point for s in sets])
    mx, my, minf = g2_to_dev([hash_to_g2(m) for m in msgs])
    r_u64, r_bits = _rand_scalars(S)
    args = (
        (jnp.asarray(px), jnp.asarray(py)), jnp.asarray(pinf),
        (jnp.asarray(sx), jnp.asarray(sy)), jnp.asarray(sinf),
        (jnp.asarray(mx), jnp.asarray(my)), jnp.asarray(minf),
        jnp.asarray(r_bits),
    )
    sched = _msm.build_schedule(r_u64, _msm.max_rounds(S))
    if sched is not None:
        args = args + (jnp.asarray(sched[0]), jnp.asarray(sched[1]))
    t0 = time.time()
    ok = bool(_verify_fused_jit(*args))
    print(f"  fused verify S={S} on TPU: {ok} ({time.time() - t0:.0f}s)",
          flush=True)
    return [] if ok else ["fused verify returned False on TPU"]


def main() -> int:
    full = "--full" in sys.argv
    run = "--run" in sys.argv
    t0 = time.time()
    fails: list[str] = []

    # Each KS mode lowers in a fresh subprocess: tkernel's traced
    # programs cache per-process, and env flips after first trace are
    # exactly the bug class this gate exists to catch.
    import subprocess

    for ks in ("0", "1"):
        print(f"[lowering-smoke] export-lower for TPU, LHTPU_KS_CARRY={ks}",
              flush=True)
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--child", ks] + (["--full"] if full else []),
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            cwd=_REPO,
        )
        if r.returncode != 0:
            fails.append(f"ks={ks}: child rc={r.returncode}")

    if run and not fails:
        print("[lowering-smoke] executing fused verify on TPU", flush=True)
        fails += _run_fused_verify()

    dt = time.time() - t0
    if fails:
        print(f"[lowering-smoke] FAILED in {dt:.0f}s:", flush=True)
        for f in fails:
            print(f"  - {f}", flush=True)
        return 1
    print(f"[lowering-smoke] PASS in {dt:.0f}s "
          f"({'full' if full else 'fast'} set, ks=0+1"
          f"{', fused verify run' if run else ''})", flush=True)
    return 0


if __name__ == "__main__":
    if "--child" in sys.argv:
        ks = sys.argv[sys.argv.index("--child") + 1]
        sys.exit(1 if _lower_all("--full" in sys.argv, ks) else 0)
    sys.exit(main())
