#!/usr/bin/env python
"""Multi-epoch soak CLI over lighthouse_tpu.loadgen.soak.

Runs ServingLoop endurance epochs under a deterministic chaos schedule
and emits one ``soak_epoch`` JSON line per epoch plus a final
``soak_verdict`` line (exit 0 iff the verdict passes). CPU-runnable on
the virtual clock; ``--wall-clock`` serves in real time on hardware.

Examples:

    # the ISSUE 7 acceptance run: 8 epochs, transient chaos at epoch 2,
    # a permanent fault at epoch 4, chaos-free digest-parity replay
    python tools/soak.py --epochs 8 \\
        --chaos "2:dispatch:transient:3;4:device_sync:permanent:1"

    # leak hunting: long steady run, no chaos, bigger streams
    python tools/soak.py --epochs 32 --committees 8 --unagg 32

The chaos grammar is ``epoch:stage:kind:count`` items joined by ``;``
(also readable from LHTPU_CHAOS_SCHEDULE); ``kind`` takes the
LHTPU_FAULT_INJECT kinds plus the ``transient``/``permanent`` aliases.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from lighthouse_tpu.common import knobs  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--chaos", default=knobs.knob("LHTPU_CHAOS_SCHEDULE"),
                    help="epoch:stage:kind:count[;...] chaos schedule")
    ap.add_argument("--seed", type=int, default=1234)
    ap.add_argument("--slots", type=int, default=2,
                    help="slots per epoch stream")
    ap.add_argument("--sps", type=float, default=2.0,
                    help="seconds per slot (pre-time_scale)")
    ap.add_argument("--time-scale", type=float, default=1.0)
    ap.add_argument("--committees", type=int, default=2)
    ap.add_argument("--committee-size", type=int, default=2)
    ap.add_argument("--unagg", type=int, default=4,
                    help="unaggregated attestations per slot")
    ap.add_argument("--sync", type=int, default=None,
                    help="sync signatures per slot (default: spec-shaped"
                         " derivation from the committee shape)")
    ap.add_argument("--weather",
                    default=knobs.knob("LHTPU_WEATHER_SCHEDULE"),
                    help="epoch:axis:value[;...] chain-weather plan "
                         "(axes: reorg_storm / non_finality / "
                         "slashing_flood / sync_boundary; epoch * = all)")
    ap.add_argument("--poison", type=float, default=0.25)
    ap.add_argument("--key-pool", type=int, default=8)
    ap.add_argument("--recovery-epochs", type=int, default=2,
                    help="re-promotion budget after the last chaos epoch")
    ap.add_argument("--wall-clock", action="store_true",
                    help="serve in real time instead of the virtual clock")
    ap.add_argument("--no-replay", action="store_true",
                    help="skip the chaos-free digest-parity replay")
    ap.add_argument("--backend", default="jax")
    args = ap.parse_args()

    # Small-bucket serving defaults (the fast-tier compile buckets):
    # explicit env always wins.
    os.environ.setdefault("LHTPU_VERDICT_GROUPS", "2")
    os.environ.setdefault("LHTPU_PIPELINE", "0")
    os.environ.setdefault("LHTPU_RETRY_BASE_MS", "0")
    # Breakers must be able to half-open within the run's wall time —
    # the stock 30 s cooldown would outlive a whole virtual soak.
    os.environ.setdefault("LHTPU_BREAKER_COOLDOWN_S", "0.05")

    # Persistent compile cache (same store as the test suite): a soak
    # measures lifetime behavior, not compile latency — epoch 0 should
    # reload the fast-tier buckets instead of paying minutes of XLA:CPU.
    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

    from lighthouse_tpu.common import resilience
    from lighthouse_tpu.loadgen.serve import ServeConfig
    from lighthouse_tpu.loadgen.soak import (
        SoakConfig, SoakRunner, parse_chaos_schedule,
    )
    from lighthouse_tpu.loadgen.traffic import TrafficConfig

    resilience.reset()  # pick up the cooldown above
    cfg = SoakConfig(
        epochs=args.epochs,
        seed=args.seed,
        backend=args.backend,
        wall_clock=args.wall_clock,
        recovery_epochs=args.recovery_epochs,
        replay=not args.no_replay,
        weather=args.weather,
        traffic=TrafficConfig(
            slots=args.slots,
            seconds_per_slot=args.sps,
            committees_per_slot=args.committees,
            committee_size=args.committee_size,
            unaggregated_per_slot=args.unagg,
            sync_per_slot=args.sync,
            poison_rate=args.poison,
            key_pool=args.key_pool,
            seed=args.seed,
            time_scale=args.time_scale,
        ),
        serve=ServeConfig.from_env(
            batch_target=max(2, args.committees * args.committee_size),
            batch_deadline_ms=250.0,
        ),
    )
    runner = SoakRunner(cfg, chaos=parse_chaos_schedule(args.chaos))
    result = runner.run()
    return 0 if result["verdict"] == "pass" else 1


if __name__ == "__main__":
    sys.exit(main())
