"""Decompose the device->e2e throughput gap (VERDICT r3 weak #6 / item 7).

The headline bench's device-only number pre-stages every operand; e2e
runs `verify_signature_sets` from SignatureSet objects. The drop between
them has three candidate sinks:

  1. host assembly  — Python/numpy work per batch: structural checks,
     pubkey/signature limb conversion (`g1_to_dev`/`g2_to_dev`),
     message dedup, CSPRNG scalars, bucketed-MSM `build_schedule`;
  2. device hashing — the SSWU+cofactor hash-to-G2 program for the
     batch's distinct messages (device-only pre-hashes; a real slot
     has ~64 distinct messages, this measures the bench's worst case
     where every set carries its own);
  3. dispatch       — per-call latency (~108 ms through the tunnel,
     hidden by pipelining in the async path).

This tool times (1) exactly as `_dispatch` runs it, component by
component, on any platform (host work is platform-independent), and —
on TPU — times (2) as the standalone `hash_to_g2_fused_dev` program.
The pipelined e2e rate then decomposes as
    1 / rate = max(host_per_batch, hash_dev + verify_dev) / S
which says which side to attack (reference analog: the worker-pool
sizing question in beacon_processor/mod.rs:1004-1070).

Usage: python tools/profile_host_share.py [S]   (default 1024)
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "")

import numpy as np


def main() -> None:
    S = int(sys.argv[1]) if len(sys.argv) > 1 else 1024

    import jax
    import jax.numpy as jnp

    # PROFILE_PLATFORM=cpu forces CPU via jax.config (the image's
    # sitecustomize overrides the JAX_PLATFORMS env var, and touching a
    # downed TPU tunnel hangs) — host-assembly timings are
    # platform-independent, so the CPU run is the fallback mode.
    plat = os.environ.get("PROFILE_PLATFORM")
    if plat:
        try:
            jax.config.update("jax_platforms", plat)
        except RuntimeError as e:
            # Proceeding onto the default (possibly hung-tunnel TPU)
            # backend is exactly what the flag exists to avoid.
            sys.exit(f"PROFILE_PLATFORM={plat} could not be applied: {e}")

    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".jax_cache_tpu",
        ),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)

    from lighthouse_tpu.crypto.bls.api import SecretKey, SignatureSet
    from lighthouse_tpu.jax_backend import _rand_scalars
    from lighthouse_tpu.ops import msm as _msm
    from lighthouse_tpu.ops.points import g1_to_dev, g2_to_dev

    print(f"building {S} signed sets (one-time, not measured)...", flush=True)
    sks = [SecretKey.from_int(i + 101) for i in range(S)]
    msgs = [i.to_bytes(32, "big") for i in range(S)]
    sets = [
        SignatureSet.single_pubkey(sk.sign(m), sk.public_key(), m)
        for sk, m in zip(sks, msgs)
    ]

    def t(label: str, fn, reps: int = 3):
        fn()  # warm (allocations, caches)
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn()
        dt = (time.perf_counter() - t0) / reps * 1e3
        print(f"  {label:34s} {dt:9.2f} ms/batch", flush=True)
        return dt, out

    print(f"host assembly components at S={S}:", flush=True)
    total = 0.0

    dt, _ = t("structural checks", lambda: [
        bool(s.signing_keys) and not s.signature.is_infinity() for s in sets
    ])
    total += dt

    dt, _ = t("pubkeys g1_to_dev", lambda: g1_to_dev(
        [s.signing_keys[0].point for s in sets]
    ))
    total += dt

    dt, _ = t("signatures g2_to_dev", lambda: g2_to_dev(
        [s.signature.point for s in sets]
    ))
    total += dt

    def dedup():
        distinct, index = [], {}
        for s in sets:
            m = s.message
            if m not in index:
                index[m] = len(distinct)
                distinct.append(m)
        return distinct

    dt, distinct = t("message dedup", dedup)
    total += dt

    # expand_message_xmd is the host half of hash-to-G2 (hashlib SHA-256);
    # the SSWU/cofactor half is the device program timed below.
    from lighthouse_tpu.crypto.bls.constants import DST
    from lighthouse_tpu.crypto.bls.hash_to_curve import expand_message_xmd

    dt, _ = t("expand_message_xmd (host SHA)", lambda: [
        expand_message_xmd(m, DST, 256) for m in distinct
    ])
    total += dt

    dt, (r_u64, r_bits) = t("CSPRNG scalars", lambda: _rand_scalars(S))
    total += dt

    dt, sched = t("MSM build_schedule", lambda: _msm.build_schedule(
        r_u64, _msm.max_rounds(S)
    ))
    total += dt

    # Upload: numpy -> device transfer of the assembled operands.
    px, py, pinf = g1_to_dev([s.signing_keys[0].point for s in sets])
    sx, sy, sinf = g2_to_dev([s.signature.point for s in sets])

    def upload():
        args = [jnp.asarray(a) for a in (px, py, pinf, sx, sy, sinf,
                                         r_bits, sched[0], sched[1])]
        jax.block_until_ready(args)
        return args

    dt, _ = t("device upload (block)", upload)
    total += dt

    print(f"  {'TOTAL host per batch':34s} {total:9.2f} ms/batch", flush=True)
    print(f"  host-implied ceiling: {S / total * 1e3:,.0f} sets/s", flush=True)

    if jax.default_backend() == "tpu":
        from lighthouse_tpu.ops.tkernel_htc import hash_to_g2_fused_dev

        def hash_dev():
            out = hash_to_g2_fused_dev(distinct)
            jax.block_until_ready(out)
            return out

        dt, _ = t("device hash-to-G2 program", hash_dev)
        print(
            f"  (device hash at D={len(distinct)} distinct msgs; the "
            f"verify program's own time is bench.py's device-only line)",
            flush=True,
        )
    else:
        print("(not on TPU: device hash-to-G2 program not timed)", flush=True)


if __name__ == "__main__":
    main()
